"""StateNode — merged view of a v1.Node and its Machine (pre-registration).

Mirrors reference pkg/controllers/state/node.go:60-334: labels/taints/capacity
resolve from the Machine until the node is initialized; ephemeral taints
(not-ready/unreachable + startup taints) are masked while uninitialized;
per-pod requests/limits with the daemonset split; nomination window.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.machine import CONDITION_MACHINE_INITIALIZED, Machine
from karpenter_core_tpu.api.settings import Settings
from karpenter_core_tpu.kube.objects import (
    LABEL_HOSTNAME,
    NamespacedName,
    Node,
    Pod,
    ResourceList,
    TAINT_NODE_NOT_READY,
    TAINT_NODE_UNREACHABLE,
    Taint,
    object_key,
)
from karpenter_core_tpu.scheduling.hostportusage import HostPortUsage
from karpenter_core_tpu.scheduling.volumeusage import VolumeCount, VolumeUsage
from karpenter_core_tpu.utils import podutils, resources


class StateNode:
    """state/node.go:60-106."""

    def __init__(self, node: Optional[Node] = None, machine: Optional[Machine] = None,
                 clock=time.time):
        self.node = node
        self.machine = machine
        self.clock = clock
        self.inflight_allocatable: ResourceList = {}
        self.inflight_capacity: ResourceList = {}
        self.startup_taints: List[Taint] = []
        self.daemonset_requests: Dict[NamespacedName, ResourceList] = {}
        self.daemonset_limits: Dict[NamespacedName, ResourceList] = {}
        self.pod_requests: Dict[NamespacedName, ResourceList] = {}
        self.pod_limits: Dict[NamespacedName, ResourceList] = {}
        self.hostport_usage = HostPortUsage()
        self.volume_usage = VolumeUsage()
        self.volume_limits = VolumeCount()
        self.marked_for_deletion = False
        self.nominated_until = 0.0

    # -- identity ---------------------------------------------------------

    def name(self) -> str:
        if not self.initialized() and self.machine is not None:
            return self.machine.name
        return self.node.name if self.node else ""

    def hostname(self) -> str:
        return self.labels().get(LABEL_HOSTNAME) or self.name()

    def provider_id(self) -> str:
        if self.node is not None and self.node.spec.provider_id:
            return self.node.spec.provider_id
        if self.machine is not None:
            return self.machine.status.provider_id
        return ""

    def labels(self) -> Dict[str, str]:
        if not self.initialized() and self.machine is not None:
            return self.machine.metadata.labels
        return self.node.metadata.labels if self.node else {}

    def annotations(self) -> Dict[str, str]:
        if not self.initialized() and self.machine is not None:
            return self.machine.metadata.annotations
        return self.node.metadata.annotations if self.node else {}

    # -- lifecycle --------------------------------------------------------

    def initialized(self) -> bool:
        """node.go:181-192."""
        if self.machine is not None:
            return self.node is not None and self.machine.condition_true(
                CONDITION_MACHINE_INITIALIZED
            )
        if self.node is not None:
            return self.node.metadata.labels.get(api_labels.LABEL_NODE_INITIALIZED) == "true"
        return False

    def owned(self) -> bool:
        return self.labels().get(api_labels.PROVISIONER_NAME_LABEL_KEY, "") != ""

    def is_marked_for_deletion(self) -> bool:
        return (
            self.marked_for_deletion
            or (self.machine is not None and self.machine.metadata.deletion_timestamp is not None)
            or (
                self.node is not None
                and self.machine is None
                and self.node.metadata.deletion_timestamp is not None
            )
        )

    def nominate(self, settings: Optional[Settings] = None) -> None:
        self.nominated_until = self.clock() + nomination_window(settings)

    def nominated(self) -> bool:
        return self.nominated_until > self.clock()

    # -- scheduling views -------------------------------------------------

    def taints(self) -> List[Taint]:
        """Ephemeral/startup-taint masking (node.go:148-176)."""
        ephemeral = [
            Taint(key=TAINT_NODE_NOT_READY, effect="NoSchedule"),
            Taint(key=TAINT_NODE_UNREACHABLE, effect="NoSchedule"),
        ]
        if not self.initialized() and self.owned():
            if self.machine is not None:
                ephemeral.extend(self.machine.spec.startup_taints)
            else:
                ephemeral.extend(self.startup_taints)
        if not self.initialized() and self.machine is not None:
            taints = self.machine.spec.taints
        else:
            taints = self.node.spec.taints if self.node else []
        return [
            t
            for t in taints
            if not any(
                e.key == t.key and e.value == t.value and e.effect == t.effect for e in ephemeral
            )
        ]

    def capacity(self) -> ResourceList:
        """node.go:194-221 — machine/inflight values backfill zero node values."""
        return self._capacity_like(
            node_view=lambda n: n.status.capacity,
            machine_view=lambda m: m.status.capacity,
            inflight=self.inflight_capacity,
        )

    def allocatable(self) -> ResourceList:
        return self._capacity_like(
            node_view=lambda n: n.status.allocatable,
            machine_view=lambda m: m.status.allocatable,
            inflight=self.inflight_allocatable,
        )

    def _capacity_like(self, node_view, machine_view, inflight) -> ResourceList:
        if not self.initialized() and self.machine is not None:
            if self.node is not None:
                ret = dict(node_view(self.node))
                for name, q in machine_view(self.machine).items():
                    if not ret.get(name):
                        ret[name] = q
                return ret
            return dict(machine_view(self.machine))
        if not self.initialized() and self.owned() and self.node is not None:
            ret = dict(node_view(self.node))
            for name, q in inflight.items():
                if not ret.get(name):
                    ret[name] = q
            return ret
        return dict(node_view(self.node)) if self.node else {}

    def available(self) -> ResourceList:
        return resources.subtract(self.allocatable(), self.total_pod_requests())

    def total_pod_requests(self) -> ResourceList:
        return resources.merge(*self.pod_requests.values()) if self.pod_requests else {}

    def total_pod_limits(self) -> ResourceList:
        return resources.merge(*self.pod_limits.values()) if self.pod_limits else {}

    def total_daemonset_requests(self) -> ResourceList:
        return resources.merge(*self.daemonset_requests.values()) if self.daemonset_requests else {}

    def total_daemonset_limits(self) -> ResourceList:
        return resources.merge(*self.daemonset_limits.values()) if self.daemonset_limits else {}

    # -- pod bookkeeping (node.go:293-321) --------------------------------

    def update_for_pod(self, pod: Pod) -> None:
        key = object_key(pod)
        self.pod_requests[key] = resources.requests_for_pods(pod)
        self.pod_limits[key] = resources.limits_for_pods(pod)
        if podutils.is_owned_by_daemonset(pod):
            self.daemonset_requests[key] = resources.requests_for_pods(pod)
            self.daemonset_limits[key] = resources.limits_for_pods(pod)
        self.hostport_usage.add(pod)
        self.volume_usage.add(pod)

    def cleanup_for_pod(self, key: NamespacedName) -> None:
        self.hostport_usage.delete_pod(key)
        self.volume_usage.delete_pod(key)
        self.pod_requests.pop(key, None)
        self.pod_limits.pop(key, None)
        self.daemonset_requests.pop(key, None)
        self.daemonset_limits.pop(key, None)

    def deep_copy(self) -> "StateNode":
        import copy as copy_mod

        out = StateNode(copy_mod.deepcopy(self.node), copy_mod.deepcopy(self.machine),
                        clock=self.clock)
        out.inflight_allocatable = dict(self.inflight_allocatable)
        out.inflight_capacity = dict(self.inflight_capacity)
        out.startup_taints = list(self.startup_taints)
        out.daemonset_requests = {k: dict(v) for k, v in self.daemonset_requests.items()}
        out.daemonset_limits = {k: dict(v) for k, v in self.daemonset_limits.items()}
        out.pod_requests = {k: dict(v) for k, v in self.pod_requests.items()}
        out.pod_limits = {k: dict(v) for k, v in self.pod_limits.items()}
        out.hostport_usage = self.hostport_usage.deep_copy()
        out.volume_usage = self.volume_usage.deep_copy()
        out.volume_limits = VolumeCount(self.volume_limits)
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        return out


def nomination_window(settings: Optional[Settings] = None) -> float:
    """max(10s, 2 x batchMaxDuration) — node.go:328-334."""
    from karpenter_core_tpu.api.settings import current

    s = settings or current()
    return max(10.0, 2.0 * s.batch_max_duration)


def populate_volume_limits_from(kube_client, state_node: "StateNode") -> None:
    """THE CSINode -> volume_limits rule (reference cluster.go:430-444):
    copy each driver's allocatable count onto the state node. Shared by
    the cluster informer (which re-applies it on every node update) and
    resolve_volume_limits below, so the resolution rule cannot drift."""
    if state_node.node is None:
        return
    csinode = kube_client.get("CSINode", "", state_node.node.metadata.name)
    if csinode is None:
        return
    for driver in csinode.drivers:
        if driver.allocatable_count is not None:
            state_node.volume_limits[driver.name] = driver.allocatable_count


def resolve_volume_limits(state_nodes, kube_client) -> None:
    """Fill EMPTY StateNode.volume_limits from the kube CSINode objects.
    Solvers consuming state_nodes that did not come from a synced Cluster
    (direct API use, the gRPC service boundary, tests) would otherwise
    treat every existing node as unlimited and overfill CSI attach
    capacity.

    Already-populated nodes are left untouched: cluster-synced snapshots
    carry informer-fresh limits (the informer re-applies the rule on
    every node update), and refreshing them here would issue one client
    get per existing node per solve — a REST storm through the apiserver
    transport. The contract this relies on: StateNode lists handed to a
    solve are per-solve SNAPSHOTS (every caller builds or deep-copies
    them per request); a bypass-path caller must not reuse StateNode
    objects across solves while CSINode limits change underneath."""
    if kube_client is None:
        return
    for sn in state_nodes or []:
        if sn.node is None or sn.volume_limits:
            continue
        populate_volume_limits_from(kube_client, sn)
