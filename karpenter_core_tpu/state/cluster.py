"""Cluster — the in-memory mirror of nodes/machines/pod-bindings.

Mirrors reference pkg/controllers/state/cluster.go:44-532: a lock-guarded map
providerID -> StateNode kept fresh by the informer controllers, pod->node
bindings, an anti-affinity pod index, node nomination, mark-for-deletion, and
the consolidation dirty-bit with a 5-minute forced re-check.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

from karpenter_core_tpu import chaos
from karpenter_core_tpu.api.machine import Machine
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.kube.objects import NamespacedName, Node, Pod, object_key
from karpenter_core_tpu.state.node import StateNode
from karpenter_core_tpu.utils import podutils


class Cluster:
    """cluster.go:44-60."""

    CONSOLIDATED_TTL = 5 * 60.0  # forced re-check interval (cluster.go:277-286)
    # delta-feed history bound: consumers further behind than this many
    # mutations get a full-resync verdict instead of a partial diff
    CHANGE_RING = 8192

    def __init__(self, kube_client, cloud_provider=None, clock=time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.clock = clock
        self._mu = threading.RLock()
        self.nodes_by_provider_id: Dict[str, StateNode] = {}
        self.node_name_to_provider_id: Dict[str, str] = {}
        self.machine_name_to_provider_id: Dict[str, str] = {}
        self.bindings: Dict[NamespacedName, str] = {}  # pod -> node name
        self.anti_affinity_pods: Dict[NamespacedName, Pod] = {}
        self._consolidated: bool = False
        self._consolidated_at: float = 0.0
        # diff feed (the incremental solver's gate): every mutation bumps
        # the revision and appends (revision, token) — token is the touched
        # node's provider id, or "*" for churn with no single node scope
        # (provisioner updates, deletes of unknown names)
        self._revision: int = 0
        self._changes = deque(maxlen=self.CHANGE_RING)

    # -- diff feed (incremental re-solve) ----------------------------------

    def _record_change(self, token: str) -> None:
        """Append one delta to the bounded feed (call under self._mu)."""
        self._revision += 1
        self._changes.append((self._revision, token))

    def revision(self) -> int:
        with self._mu:
            return self._revision

    def changes_since(self, cursor: Optional[int]) -> Tuple[int, Optional[Set[str]]]:
        """The state-store delta feed: (new_cursor, changed tokens since
        `cursor`), or (new_cursor, None) when the feed cannot prove it has
        full history — cursor None/unknown, or older than the bounded ring
        remembers — and the consumer must treat the world as fully changed.

        Tokens are node provider ids plus the "*" sentinel for unscoped
        churn. Delivery is at-least-once by construction (tokens are a
        set, duplicated deltas collapse); DROPPED deltas are impossible
        within ring history because revisions are dense — a gap between
        the cursor and the oldest retained revision is detected and
        reported as a full resync, never silently skipped.

        chaos fault point `state.diff` models a feed that lies (dropped /
        duplicated / reordered deliveries from a flaky store): the injected
        error propagates to the caller, whose contract is to degrade to the
        full re-encode path rather than trust this diff."""
        chaos.maybe_fail(chaos.STATE_DIFF)
        with self._mu:
            rev = self._revision
            if cursor is None or cursor > rev:
                return rev, None
            if cursor == rev:
                return rev, set()
            oldest = self._changes[0][0] if self._changes else rev + 1
            if cursor + 1 < oldest:
                return rev, None  # history fell off the ring
            # revisions are dense and the ring is append-ordered: walk the
            # tail back to the cursor instead of scanning all 8192 entries
            changed: Set[str] = set()
            for r, t in reversed(self._changes):
                if r <= cursor:
                    break
                changed.add(t)
            return rev, changed

    # -- queries (cluster.go:116-202) --------------------------------------

    def nodes(self) -> List[StateNode]:
        """Deep-copied snapshot (cluster.go:149-156)."""
        with self._mu:
            return [n.deep_copy() for n in self.nodes_by_provider_id.values()]

    def for_each_node(self, fn: Callable[[StateNode], bool]) -> None:
        with self._mu:
            nodes = list(self.nodes_by_provider_id.values())
        for node in nodes:
            if not fn(node):
                return

    def for_pods_with_anti_affinity(self, fn: Callable[[Pod, Node], bool]) -> None:
        """Visit scheduled pods carrying required anti-affinity
        (cluster.go:116-132)."""
        with self._mu:
            pods = list(self.anti_affinity_pods.values())
        for pod in pods:
            node = self.kube_client.get("Node", "", pod.spec.node_name)
            if node is None:
                continue
            if not fn(pod, node):
                return

    def node_for(self, name: str) -> Optional[StateNode]:
        with self._mu:
            pid = self.node_name_to_provider_id.get(name) or self.machine_name_to_provider_id.get(
                name
            )
            if pid is None:
                return None
            return self.nodes_by_provider_id.get(pid)

    # -- nomination (cluster.go:160-178) -----------------------------------

    def nominate_node_for_pod(self, node_name: str) -> None:
        with self._mu:
            node = self.node_for(node_name)
            if node is not None:
                node.nominate()

    def unmark_for_deletion(self, *node_names: str) -> None:
        with self._mu:
            for name in node_names:
                node = self.node_for(name)
                if node is not None:
                    node.marked_for_deletion = False
                    self._record_change(node.provider_id() or "*")

    def mark_for_deletion(self, *node_names: str) -> None:
        """cluster.go:181-202."""
        with self._mu:
            for name in node_names:
                node = self.node_for(name)
                if node is not None:
                    node.marked_for_deletion = True
                    self._record_change(node.provider_id() or "*")

    # -- consolidation dirty bit (cluster.go:269-286) ----------------------

    def set_consolidated(self, consolidated: bool) -> None:
        with self._mu:
            self._consolidated = consolidated
            if consolidated:
                self._consolidated_at = self.clock()

    def consolidated(self) -> bool:
        """True while nothing changed since the last full consolidation scan,
        force-expiring every 5 minutes."""
        with self._mu:
            if self.clock() - self._consolidated_at > self.CONSOLIDATED_TTL:
                self._consolidated = False
            return self._consolidated

    # -- ingestion (cluster.go:204-267,341-505) ----------------------------

    def update_node(self, node: Node) -> None:
        with self._mu:
            provider_id = node.spec.provider_id or f"node:///{node.metadata.name}"
            existing = self.nodes_by_provider_id.get(provider_id)
            if existing is None:
                existing = StateNode(node=node, clock=self.clock)
                # PVC -> driver resolution needs the store (volumeusage.go
                # resolves through the kube client)
                existing.volume_usage.kube_client = self.kube_client
                self.nodes_by_provider_id[provider_id] = existing
            else:
                existing.node = node
            self.node_name_to_provider_id[node.metadata.name] = provider_id
            self._populate_inflight(existing)
            self._populate_volume_limits(existing)
            self._record_change(provider_id)
            self.set_consolidated(False)

    def delete_node(self, name: str) -> None:
        with self._mu:
            pid = self.node_name_to_provider_id.pop(name, None)
            if pid is not None:
                state_node = self.nodes_by_provider_id.get(pid)
                if state_node is not None:
                    if state_node.machine is not None:
                        state_node.node = None  # machine record remains
                    else:
                        del self.nodes_by_provider_id[pid]
            self._record_change(pid or "*")
            self.set_consolidated(False)

    def update_machine(self, machine: Machine) -> None:
        with self._mu:
            if not machine.status.provider_id:
                # can't reconcile machines without provider ids yet
                # (cluster.go:204-210); synced() skips them for the same
                # reason, so they don't block startup either
                return
            provider_id = machine.status.provider_id
            existing = self.nodes_by_provider_id.get(provider_id)
            if existing is None:
                existing = StateNode(machine=machine, clock=self.clock)
                self.nodes_by_provider_id[provider_id] = existing
            else:
                existing.machine = machine
            self.machine_name_to_provider_id[machine.name] = provider_id
            self._record_change(provider_id)
            self.set_consolidated(False)

    def delete_machine(self, name: str) -> None:
        with self._mu:
            pid = self.machine_name_to_provider_id.pop(name, None)
            if pid is not None:
                state_node = self.nodes_by_provider_id.get(pid)
                if state_node is not None:
                    if state_node.node is not None:
                        state_node.machine = None
                    else:
                        del self.nodes_by_provider_id[pid]
            self._record_change(pid or "*")
            self.set_consolidated(False)

    def update_pod(self, pod: Pod) -> None:
        """cluster.go:446-505: maintain bindings, per-node usage, and the
        anti-affinity index."""
        with self._mu:
            key = object_key(pod)
            if podutils.is_terminal(pod):
                self._unbind(key)
                self.anti_affinity_pods.pop(key, None)
                self.set_consolidated(False)
                return
            old_node_name = self.bindings.get(key)
            if pod.spec.node_name:
                if old_node_name and old_node_name != pod.spec.node_name:
                    self._unbind(key)
                self.bindings[key] = pod.spec.node_name
                node = self.node_for(pod.spec.node_name)
                if node is not None:
                    node.update_for_pod(pod)
                    self._record_change(node.provider_id() or "*")
                if podutils.has_pod_anti_affinity(pod):
                    self.anti_affinity_pods[key] = pod
            self.set_consolidated(False)

    def delete_pod(self, key: NamespacedName) -> None:
        with self._mu:
            self._unbind(key)
            self.anti_affinity_pods.pop(key, None)
            self.set_consolidated(False)

    def update_provisioner(self, provisioner: Provisioner) -> None:
        # cache-invalidate only (informer/provisioner.go:52); unscoped for
        # the diff feed — templates, not node rows, but consumers keyed on
        # node deltas alone must still see that SOMETHING moved
        with self._mu:
            self._record_change("*")
        self.set_consolidated(False)

    def synced(self) -> bool:
        """All kube nodes/machines are reflected (cluster.go:77-111)."""
        with self._mu:
            for node in self.kube_client.list("Node"):
                if node.metadata.name not in self.node_name_to_provider_id:
                    return False
            for machine in self.kube_client.list("Machine"):
                if machine.status.provider_id and machine.metadata.name not in (
                    self.machine_name_to_provider_id
                ):
                    return False
            return True

    # -- internals ---------------------------------------------------------

    def _unbind(self, key: NamespacedName) -> None:
        node_name = self.bindings.pop(key, None)
        if node_name:
            node = self.node_for(node_name)
            if node is not None:
                node.cleanup_for_pod(key)
                # a termination FREES a slot — the delta the incremental
                # re-solve narrows its refresh to
                self._record_change(node.provider_id() or "*")

    def _populate_inflight(self, state_node: StateNode) -> None:
        """Inflight capacity from the instance type until kubelet reports
        (cluster.go:388-428)."""
        if self.cloud_provider is None or state_node.node is None:
            return
        from karpenter_core_tpu.kube.objects import LABEL_INSTANCE_TYPE_STABLE

        it_name = state_node.labels().get(LABEL_INSTANCE_TYPE_STABLE)
        if not it_name:
            return
        try:
            for it in self.cloud_provider.get_instance_types(None):
                if it.name == it_name:
                    state_node.inflight_capacity = dict(it.capacity)
                    state_node.inflight_allocatable = dict(it.allocatable())
                    break
        except Exception:
            pass

    def _populate_volume_limits(self, state_node: StateNode) -> None:
        """CSINode driver limits (cluster.go:430-444) — the shared rule,
        re-applied on every node update so limits stay informer-fresh."""
        from karpenter_core_tpu.state.node import populate_volume_limits_from

        populate_volume_limits_from(self.kube_client, state_node)
