"""Informer controllers: pump kube watch events into the Cluster state.

Mirrors reference pkg/controllers/state/informer/{node,pod,machine,
provisioner}.go:51-53 — thin reconcilers translating apiserver watch events
into Cluster.Update*/Delete* calls.
"""
from __future__ import annotations

from karpenter_core_tpu.kube.objects import NamespacedName


class NodeInformer:
    def __init__(self, cluster):
        self.cluster = cluster

    def handle(self, event: str, node) -> None:
        if event == "DELETED":
            self.cluster.delete_node(node.metadata.name)
        else:
            self.cluster.update_node(node)


class PodInformer:
    def __init__(self, cluster):
        self.cluster = cluster

    def handle(self, event: str, pod) -> None:
        if event == "DELETED":
            self.cluster.delete_pod(NamespacedName(pod.metadata.namespace, pod.metadata.name))
        else:
            self.cluster.update_pod(pod)


class MachineInformer:
    def __init__(self, cluster):
        self.cluster = cluster

    def handle(self, event: str, machine) -> None:
        if event == "DELETED":
            self.cluster.delete_machine(machine.metadata.name)
        else:
            self.cluster.update_machine(machine)


class ProvisionerInformer:
    def __init__(self, cluster):
        self.cluster = cluster

    def handle(self, event: str, provisioner) -> None:
        self.cluster.update_provisioner(provisioner)
