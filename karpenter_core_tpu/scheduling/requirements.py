"""Requirements — a map key -> Requirement closed under intersection.

Mirrors reference pkg/scheduling/requirements.go:32-223: `add` intersects with
any existing requirement for the same key; `compatible` enforces that custom
(non-well-known) labels must be defined on the node side while well-known
labels intersect-if-present; `intersects` is the symmetric overlap check with
the NotIn/DoesNotExist escape hatch.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Optional

from karpenter_core_tpu.kube.objects import Pod
from karpenter_core_tpu.scheduling.requirement import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    Requirement,
)


class Requirements(Dict[str, Requirement]):
    """dict[key, Requirement] with intersection-on-add (requirements.go:32)."""

    def __init__(self, requirements: Iterable[Requirement] = ()):
        super().__init__()
        self.add(*requirements)

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_node_selector_requirements(cls, *reqs) -> "Requirements":
        """From kube NodeSelectorRequirement objects (requirements.go:43-49)."""
        return cls(Requirement(r.key, r.operator, r.values) for r in reqs)

    @classmethod
    def from_labels(cls, labels: Dict[str, str]) -> "Requirements":
        """Each label k=v becomes In(v) (requirements.go:52-58)."""
        return cls(Requirement(k, OP_IN, [v]) for k, v in labels.items())

    @classmethod
    def from_pod(cls, pod: Pod) -> "Requirements":
        """nodeSelector + heaviest preferred term + FIRST required term
        (requirements.go:61-78; the relaxation loop drops the rest)."""
        requirements = cls.from_labels(pod.spec.node_selector)
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None:
            return requirements
        node_affinity = affinity.node_affinity
        if node_affinity.preferred:
            heaviest = max(node_affinity.preferred, key=lambda t: t.weight)
            requirements.add(
                *cls.from_node_selector_requirements(
                    *heaviest.preference.match_expressions
                ).values()
            )
        if node_affinity.required:
            requirements.add(
                *cls.from_node_selector_requirements(
                    *node_affinity.required[0].match_expressions
                ).values()
            )
        return requirements

    # -- algebra -----------------------------------------------------------

    def add(self, *requirements: Requirement) -> None:
        """Intersecting add (requirements.go:87-94)."""
        for requirement in requirements:
            existing = super().get(requirement.key)
            if existing is not None:
                requirement = requirement.intersection(existing)
            self[requirement.key] = requirement

    def copy(self) -> "Requirements":
        return Requirements(
            Requirement._make(r.key, r.complement, r.values, r.greater_than, r.less_than)
            for r in self.values()
        )

    def keys_set(self) -> frozenset:
        return frozenset(self.keys())

    def get_requirement(self, key: str) -> Requirement:
        """Missing keys read as Exists — allow anything (requirements.go:114-120)."""
        existing = super().get(key)
        if existing is None:
            return Requirement(key, OP_EXISTS)
        return existing

    def compatible(self, requirements: "Requirements") -> Optional[str]:
        """None if `requirements` can be met, else an error string
        (requirements.go:123-133). Custom labels must be defined on the
        receiver (node side) unless the incoming operator is NotIn or
        DoesNotExist; well-known labels intersect-if-present."""
        from karpenter_core_tpu.api.labels import WELL_KNOWN_LABELS

        errs: List[str] = []
        for key in requirements.keys_set() - WELL_KNOWN_LABELS:
            op = requirements.get_requirement(key).operator()
            if key in self or op in (OP_NOT_IN, OP_DOES_NOT_EXIST):
                continue
            errs.append(
                f'label "{key}" does not have known values'
                + self._label_hint(key)
            )
        err = self.intersects(requirements)
        if err:
            errs.append(err)
        return "; ".join(errs) if errs else None

    def _label_hint(self, key: str) -> str:
        """Typo suggestion for an unknown label: a well-known (then
        existing) label that contains the key or sits within 1/5 of its
        length in edit distance (requirements.go:172-186). Sorted
        iteration keeps the suggestion deterministic where Go's map order
        is not. The well-known scan is memoized — hot-loop callers
        (machine.add per pod x slot x relaxation round) only test the
        returned string for truthiness, so the Levenshtein work must not
        repeat per call."""
        from karpenter_core_tpu.api.labels import WELL_KNOWN_LABELS

        hint = _well_known_hint(key, tuple(sorted(WELL_KNOWN_LABELS)))
        if hint:
            return hint
        for existing in sorted(self.keys()):
            if key in existing or _edit_distance(key, existing) < len(existing) // 5:
                return f' (typo of "{existing}"?)'
        return ""

    def intersects(self, requirements: "Requirements") -> Optional[str]:
        """None if overlapping values exist for every shared key
        (requirements.go:189-206)."""
        errs: List[str] = []
        for key in self.keys_set() & requirements.keys_set():
            existing = self.get_requirement(key)
            incoming = requirements.get_requirement(key)
            if existing.intersection(incoming).len() == 0:
                # NotIn/DoesNotExist on BOTH sides is vacuously fine
                if incoming.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST) and existing.operator() in (
                    OP_NOT_IN,
                    OP_DOES_NOT_EXIST,
                ):
                    continue
                errs.append(f"key {key}, {incoming!r} not in {existing!r}")
        return "; ".join(errs) if errs else None

    def labels(self) -> Dict[str, str]:
        """Representative node labels (requirements.go:208-218)."""
        from karpenter_core_tpu.api.labels import is_restricted_node_label

        out: Dict[str, str] = {}
        for key, requirement in self.items():
            if not is_restricted_node_label(key):
                value = requirement.any()
                if value:
                    out[key] = value
        return out

    def __repr__(self) -> str:
        from karpenter_core_tpu.api.labels import RESTRICTED_LABELS

        shown = [r for k, r in sorted(self.items()) if k not in RESTRICTED_LABELS]
        return ", ".join(repr(r) for r in shown)


@functools.lru_cache(maxsize=4096)
def _well_known_hint(key: str, known_sorted: tuple) -> str:
    for known in known_sorted:
        if key in known or _edit_distance(key, known) < len(known) // 5:
            return f' (typo of "{known}"?)'
    return ""


def _edit_distance(s: str, t: str) -> int:
    """Levenshtein distance (requirements.go:135-165's editDistance)."""
    if not s:
        return len(t)
    if not t:
        return len(s)
    prev = list(range(len(t) + 1))
    for i, cs in enumerate(s, start=1):
        cur = [i] + [0] * len(t)
        for j, ct in enumerate(t, start=1):
            cur[j] = min(
                prev[j] + 1,
                cur[j - 1] + 1,
                prev[j - 1] + (0 if cs == ct else 1),
            )
        prev = cur
    return prev[-1]
