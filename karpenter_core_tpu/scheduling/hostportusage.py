"""Per-node unique (hostIP, hostPort, protocol) reservation with
validate-before-add (reference pkg/scheduling/hostportusage.go:29-145)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.kube.objects import NamespacedName, Pod, object_key

_UNSPECIFIED = ("", "0.0.0.0", "::")


@dataclass(frozen=True)
class HostPortEntry:
    ip: str
    port: int
    protocol: str

    def matches(self, other: "HostPortEntry") -> bool:
        """hostportusage.go:42-54 — unspecified IPs conflict with everything."""
        if self.protocol != other.protocol:
            return False
        if self.port != other.port:
            return False
        if self.ip != other.ip and self.ip not in _UNSPECIFIED and other.ip not in _UNSPECIFIED:
            return False
        return True

    def __str__(self) -> str:
        return f"IP={self.ip} Port={self.port} Proto={self.protocol}"


def host_ports(pod: Pod) -> List[HostPortEntry]:
    """hostportusage.go:117-140 — hostIP defaults to 0.0.0.0, proto to TCP."""
    usage = []
    for container in pod.spec.containers:
        for port in container.ports:
            if port.host_port == 0:
                continue
            usage.append(
                HostPortEntry(
                    ip=port.host_ip or "0.0.0.0",
                    port=port.host_port,
                    protocol=port.protocol or "TCP",
                )
            )
    return usage


class HostPortUsage:
    """hostportusage.go:29-115."""

    def __init__(self):
        self.reserved: Dict[NamespacedName, List[HostPortEntry]] = {}

    def add(self, pod: Pod) -> None:
        new_usage, _ = self._validate(pod)
        self.reserved[object_key(pod)] = new_usage

    def validate(self, pod: Pod) -> Optional[str]:
        _, err = self._validate(pod)
        return err

    def _validate(self, pod: Pod) -> Tuple[List[HostPortEntry], Optional[str]]:
        new_usage = host_ports(pod)
        pod_key = object_key(pod)
        for new_entry in new_usage:
            for key, entries in self.reserved.items():
                if key == pod_key:
                    continue
                for existing in entries:
                    if new_entry.matches(existing):
                        return (
                            [],
                            f"{new_entry} conflicts with existing HostPort configuration {existing}",
                        )
        return new_usage, None

    def delete_pod(self, key: NamespacedName) -> None:
        self.reserved.pop(key, None)

    def deep_copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        out.reserved = {k: list(v) for k, v in self.reserved.items()}
        return out
