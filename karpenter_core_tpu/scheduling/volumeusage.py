"""Per-node mounted-volume counting per CSI driver vs CSINode limits
(reference pkg/scheduling/volumeusage.go:33-236).

The reference resolves a pod's PVC -> PV/StorageClass -> CSI driver via the
kube client; here the lookup goes through the in-memory kube store.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Set

from karpenter_core_tpu.kube.objects import NamespacedName, Pod, object_key

Volumes = Dict[str, Set[str]]  # csi driver name -> set of pvc ids


def _union(a: Volumes, b: Volumes) -> Volumes:
    out = {k: set(v) for k, v in a.items()}
    for k, v in b.items():
        out.setdefault(k, set()).update(v)
    return out


class VolumeCount(dict):
    """driver -> count; exceeds() compares against CSINode limits
    (volumeusage.go:102-131)."""

    def exceeds(self, limits: Dict[str, int]) -> bool:
        for driver, count in self.items():
            limit = limits.get(driver)
            if limit is not None and count > limit:
                return True
        return False


class VolumeUsage:
    """volumeusage.go:33-100."""

    def __init__(self, kube_client=None):
        self.kube_client = kube_client
        self.volumes: Volumes = {}
        self.pod_volumes: Dict[NamespacedName, Volumes] = {}

    def add(self, pod: Pod) -> None:
        pod_vols = self._resolve(pod)
        self.pod_volumes[object_key(pod)] = pod_vols
        self.volumes = _union(self.volumes, pod_vols)

    def validate(self, pod: Pod) -> VolumeCount:
        """Projected per-driver counts if the pod were added."""
        pod_vols = self._resolve(pod)
        merged = _union(self.volumes, pod_vols)
        result = VolumeCount()
        for driver, ids in merged.items():
            result[driver] = len(ids)
        return result

    def delete_pod(self, key: NamespacedName) -> None:
        self.pod_volumes.pop(key, None)
        self.volumes = {}
        for vols in self.pod_volumes.values():
            self.volumes = _union(self.volumes, vols)

    def deep_copy(self) -> "VolumeUsage":
        out = VolumeUsage(self.kube_client)
        out.volumes = {k: set(v) for k, v in self.volumes.items()}
        out.pod_volumes = {
            pk: {k: set(v) for k, v in vols.items()} for pk, vols in self.pod_volumes.items()
        }
        return out

    def _resolve(self, pod: Pod) -> Volumes:
        """PVC -> (bound PV).csi.driver or StorageClass.provisioner
        (volumeusage.go:133-200)."""
        result: Volumes = {}
        if self.kube_client is None:
            return result
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim is None:
                continue
            claim_name = volume.persistent_volume_claim.claim_name
            pvc = self.kube_client.get(
                "PersistentVolumeClaim", pod.metadata.namespace, claim_name
            )
            if pvc is None:
                continue
            pvc_id = f"{pod.metadata.namespace}/{claim_name}"
            driver = self._driver_for(pvc)
            if driver:
                result.setdefault(driver, set()).add(pvc_id)
        return result

    def _driver_for(self, pvc) -> Optional[str]:
        if pvc.spec.volume_name:
            pv = self.kube_client.get("PersistentVolume", "", pvc.spec.volume_name)
            if pv is not None and pv.spec.csi is not None:
                return pv.spec.csi.driver
        if pvc.spec.storage_class_name:
            sc = self.kube_client.get("StorageClass", "", pvc.spec.storage_class_name)
            if sc is not None:
                return sc.provisioner
        return None
