"""Soft-constraint relaxation in fixed order (reference preferences.go:36-145).

Order: required node-affinity term (OR semantics — drop head term) →
preferred pod-affinity → preferred pod-anti-affinity → preferred node-affinity
(heaviest first) → ScheduleAnyway topology spreads → (optionally) tolerate
PreferNoSchedule taints.
"""
from __future__ import annotations

from typing import Optional

from karpenter_core_tpu.kube.objects import Pod, Toleration


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod: Pod) -> bool:
        relaxations = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_topology_spread_schedule_anyway,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule_taints)
        for relax_fn in relaxations:
            if relax_fn(pod) is not None:
                return True
        return False

    def is_relaxable(self, pod: Pod) -> bool:
        """True when relax(pod) would still change something — i.e. the pod
        carries at least one soft constraint the fixed order can drop.
        Non-mutating; used to decide whether an unrelaxed screening solve
        (solver/replan.py) can be trusted as a conclusive negative."""
        affinity = pod.spec.affinity
        if affinity is not None:
            node_aff = affinity.node_affinity
            if node_aff is not None and (len(node_aff.required) > 1 or node_aff.preferred):
                return True
            if affinity.pod_affinity is not None and affinity.pod_affinity.preferred:
                return True
            if (
                affinity.pod_anti_affinity is not None
                and affinity.pod_anti_affinity.preferred
            ):
                return True
        return any(
            tsc.when_unsatisfiable == "ScheduleAnyway"
            for tsc in pod.spec.topology_spread_constraints
        )

    def _remove_required_node_affinity_term(self, pod: Pod) -> Optional[str]:
        """Required terms are ORed; drop the head term only while >1 remain
        (preferences.go:73-86)."""
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None or len(affinity.node_affinity.required) <= 1:
            return None
        dropped = affinity.node_affinity.required[0]
        affinity.node_affinity.required = affinity.node_affinity.required[1:]
        return f"removed required node affinity term {dropped}"

    def _remove_preferred_pod_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if affinity is None or affinity.pod_affinity is None or not affinity.pod_affinity.preferred:
            return None
        terms = sorted(affinity.pod_affinity.preferred, key=lambda t: -t.weight)
        affinity.pod_affinity.preferred = terms[1:]
        return f"removed preferred pod affinity term {terms[0]}"

    def _remove_preferred_pod_anti_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if (
            affinity is None
            or affinity.pod_anti_affinity is None
            or not affinity.pod_anti_affinity.preferred
        ):
            return None
        terms = sorted(affinity.pod_anti_affinity.preferred, key=lambda t: -t.weight)
        affinity.pod_anti_affinity.preferred = terms[1:]
        return f"removed preferred pod anti-affinity term {terms[0]}"

    def _remove_preferred_node_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None or not affinity.node_affinity.preferred:
            return None
        terms = sorted(affinity.node_affinity.preferred, key=lambda t: -t.weight)
        affinity.node_affinity.preferred = terms[1:]
        return f"removed preferred node affinity term {terms[0]}"

    def _remove_topology_spread_schedule_anyway(self, pod: Pod) -> Optional[str]:
        for i, tsc in enumerate(pod.spec.topology_spread_constraints):
            if tsc.when_unsatisfiable == "ScheduleAnyway":
                pod.spec.topology_spread_constraints.pop(i)
                return f"removed ScheduleAnyway topology spread {tsc}"
        return None

    def _tolerate_prefer_no_schedule_taints(self, pod: Pod) -> Optional[str]:
        """preferences.go:131-145."""
        for t in pod.spec.tolerations:
            if t.operator == "Exists" and t.effect == "PreferNoSchedule" and t.key == "":
                return None
        pod.spec.tolerations.append(Toleration(operator="Exists", effect="PreferNoSchedule"))
        return "added toleration for PreferNoSchedule taints"
