"""Per-Provisioner launch template (reference machinetemplate.go:32-62).

Lives in the neutral scheduling layer because BOTH sides of the solve
boundary construct it: the host scheduler's in-flight machines
(controllers/provisioning/scheduling/machine.py) and the tensor encoder
(solver/encode.py) — the solver must never reach up into controllers/ for
a shared domain type (layering pass, analysis/layering.py).
"""
from __future__ import annotations

import itertools
from typing import TYPE_CHECKING, List

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.machine import (
    Machine,
    MachineResourceRequirements,
    MachineSpec,
)
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    Node,
    ObjectMeta,
    Taint,
)
from karpenter_core_tpu.scheduling.requirement import OP_IN, Requirement
from karpenter_core_tpu.scheduling.requirements import Requirements

if TYPE_CHECKING:  # concrete types flow in at runtime; no import edge
    from karpenter_core_tpu.api.provisioner import Provisioner
    from karpenter_core_tpu.cloudprovider.types import InstanceType
    from karpenter_core_tpu.kube.objects import ResourceList

# Shared machine/hostname sequence: SchedulingMachine's placeholder
# hostnames and MachineTemplate.to_machine names draw from ONE counter so
# launch names stay unique and ordered across both call sites.
_node_id = itertools.count(1)


def next_node_id() -> int:
    return next(_node_id)


class MachineTemplate:
    """machinetemplate.go:32-62."""

    def __init__(self, provisioner: "Provisioner"):
        labels = dict(provisioner.spec.labels)
        labels[api_labels.PROVISIONER_NAME_LABEL_KEY] = provisioner.name
        requirements = Requirements()
        requirements.add(
            *Requirements.from_node_selector_requirements(*provisioner.spec.requirements).values()
        )
        requirements.add(*Requirements.from_labels(labels).values())
        self.provisioner_name = provisioner.name
        self.provider = provisioner.spec.provider
        self.provider_ref = provisioner.spec.provider_ref
        self.kubelet = provisioner.spec.kubelet_configuration
        self.annotations = dict(provisioner.spec.annotations)
        self.labels = labels
        self.taints: List[Taint] = list(provisioner.spec.taints)
        self.startup_taints: List[Taint] = list(provisioner.spec.startup_taints)
        self.requirements = requirements
        self.requests: "ResourceList" = {}
        self.instance_type_options: List["InstanceType"] = []

    def to_node(self) -> Node:
        """machinetemplate.go:64-77."""
        node = Node(
            metadata=ObjectMeta(
                labels={**self.labels, **self.requirements.labels()},
                annotations=dict(self.annotations),
                finalizers=[api_labels.TERMINATION_FINALIZER],
            )
        )
        node.spec.taints = list(self.taints) + list(self.startup_taints)
        return node

    def to_machine(self) -> Machine:
        """machinetemplate.go:79-100 — narrows instance-type requirement to
        the final option set; inline provider config rides the compatibility
        annotation (provisioner.go:104-112)."""
        self.requirements.add(
            Requirement(
                LABEL_INSTANCE_TYPE_STABLE,
                OP_IN,
                [it.name for it in self.instance_type_options],
            )
        )
        annotations = dict(self.annotations)
        if self.provider is not None:
            import json

            annotations[api_labels.PROVIDER_COMPATIBILITY_ANNOTATION_KEY] = json.dumps(
                self.provider, sort_keys=True
            )
        machine = Machine(
            metadata=ObjectMeta(
                name=f"{self.provisioner_name}-{next(_node_id):05d}",
                annotations=annotations,
                labels=dict(self.labels),
            ),
            spec=MachineSpec(
                taints=list(self.taints),
                startup_taints=list(self.startup_taints),
                requirements=[
                    r.to_node_selector_requirement() for r in self.requirements.values()
                ],
                resources=MachineResourceRequirements(requests=dict(self.requests)),
                kubelet=self.kubelet,
                machine_template_ref=self.provider_ref,
            ),
        )
        machine.metadata.namespace = ""
        return machine
