"""Single label-key constraint as a (possibly complemented) value set with
integer bounds — the atom of the constraint algebra.

Mirrors reference pkg/scheduling/requirement.go:36-243: a Requirement is a set
of allowed string values for one label key; `complement=True` means the set
holds *excluded* values (NotIn/Exists), closed under intersection; Gt/Lt are
carried as integer bounds that survive only on complement sets.

The TPU encoding (solver/encode.py) lowers each Requirement to a bitmask over
the key's closed value dictionary plus a complement bit and the two bounds.
"""
from __future__ import annotations

import math
import random
from typing import FrozenSet, Iterable, Optional, Set

OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"

MAX_LEN = 2**63 - 1  # stand-in for the infinite universe (requirement.go:199-204)


def _normalize_key(key: str) -> str:
    from karpenter_core_tpu.api.labels import NORMALIZED_LABELS

    return NORMALIZED_LABELS.get(key, key)


class Requirement:
    """One label key's constraint (requirement.go:36-68)."""

    __slots__ = ("key", "complement", "values", "greater_than", "less_than")

    def __init__(
        self,
        key: str,
        operator: str = OP_EXISTS,
        values: Iterable[str] = (),
        *,
        _raw: bool = False,
    ):
        if _raw:
            # internal constructor: fields assigned by caller
            self.key = key
            self.complement = True
            self.values: Set[str] = set()
            self.greater_than: Optional[int] = None
            self.less_than: Optional[int] = None
            return
        self.key = _normalize_key(key)
        self.complement = operator not in (OP_IN, OP_DOES_NOT_EXIST)
        self.values = set()
        self.greater_than = None
        self.less_than = None
        values = list(values)
        if operator in (OP_IN, OP_NOT_IN):
            self.values.update(values)
        elif operator == OP_GT:
            self.greater_than = int(values[0])
        elif operator == OP_LT:
            self.less_than = int(values[0])

    @classmethod
    def _make(cls, key, complement, values, greater_than=None, less_than=None) -> "Requirement":
        r = cls(key, _raw=True)
        r.key = key
        r.complement = complement
        r.values = set(values)
        r.greater_than = greater_than
        r.less_than = less_than
        return r

    # -- set algebra -------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """Constrain by `other`; closed under intersection
        (requirement.go:117-150)."""
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, OP_DOES_NOT_EXIST)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = {v for v in values if _within_bounds(v, greater_than, less_than)}
        if not complement:
            greater_than, less_than = None, None
        return Requirement._make(self.key, complement, values, greater_than, less_than)

    def has(self, value: str) -> bool:
        """True if the requirement allows the value (requirement.go:171-176)."""
        if self.complement:
            return value not in self.values and _within_bounds(
                value, self.greater_than, self.less_than
            )
        return value in self.values and _within_bounds(value, self.greater_than, self.less_than)

    def any(self) -> str:
        """A representative allowed value (requirement.go:152-168)."""
        op = self.operator()
        if op == OP_IN:
            return min(self.values)  # deterministic (reference picks arbitrary)
        if op in (OP_NOT_IN, OP_EXISTS):
            lo = 0 if self.greater_than is None else self.greater_than + 1
            hi = MAX_LEN if self.less_than is None else self.less_than
            if hi <= lo:
                return str(lo)
            for _ in range(32):
                v = str(random.randrange(lo, hi))
                if v not in self.values:
                    return v
            return str(lo)
        return ""

    def insert(self, *items: str) -> None:
        self.values.update(items)

    def to_node_selector_requirement(self):
        """Recover the v1.NodeSelectorRequirement form (requirement.go:70-113)."""
        from karpenter_core_tpu.kube.objects import NodeSelectorRequirement

        if self.greater_than is not None:
            return NodeSelectorRequirement(self.key, OP_GT, [str(self.greater_than)])
        if self.less_than is not None:
            return NodeSelectorRequirement(self.key, OP_LT, [str(self.less_than)])
        op = self.operator()
        if op in (OP_IN, OP_NOT_IN):
            return NodeSelectorRequirement(self.key, op, self.values_list())
        return NodeSelectorRequirement(self.key, op, [])

    def operator(self) -> str:
        """Recovered NodeSelector operator (requirement.go:186-197)."""
        if self.complement:
            return OP_NOT_IN if self.values else OP_EXISTS
        return OP_IN if self.values else OP_DOES_NOT_EXIST

    def __len__(self) -> int:
        raise TypeError("use .len() — complement sets exceed Py __len__ range")

    def len(self) -> int:
        """Cardinality; complement sets count down from MAX_LEN
        (requirement.go:199-204)."""
        if self.complement:
            return MAX_LEN - len(self.values)
        return len(self.values)

    def values_list(self):
        return sorted(self.values)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Requirement)
            and self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
        )

    def __hash__(self):
        return hash(
            (self.key, self.complement, frozenset(self.values), self.greater_than, self.less_than)
        )

    def __repr__(self) -> str:
        op = self.operator()
        if op in (OP_EXISTS, OP_DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            vals = self.values_list()
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(self.values) - 5} others"]
            s = f"{self.key} {op} {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s


def _within_bounds(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    """requirement.go:227-243 — with bounds set, non-integers are invalid."""
    if greater_than is None and less_than is None:
        return True
    try:
        v = int(value)
    except (TypeError, ValueError):
        return False
    if greater_than is not None and greater_than >= v:
        return False
    if less_than is not None and less_than <= v:
        return False
    return True


def _min_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)
