"""Taint toleration checks (reference pkg/scheduling/taints.go:26-57)."""
from __future__ import annotations

from typing import List, Optional

from karpenter_core_tpu.kube.objects import Pod, Taint


def tolerates(taints: List[Taint], pod: Pod) -> Optional[str]:
    """None if the pod tolerates ALL taints, else an error string
    (taints.go:29-41)."""
    errs = []
    for taint in taints:
        if not any(t.tolerates_taint(taint) for t in pod.spec.tolerations):
            errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
    return "; ".join(errs) if errs else None


def merge(taints: List[Taint], with_taints: List[Taint]) -> List[Taint]:
    """Union keyed on (key, effect) identity, left-biased (taints.go:44-56)."""
    result = list(taints)
    for taint in with_taints:
        if not any(taint.match_taint(t) for t in result):
            result.append(taint)
    return result
