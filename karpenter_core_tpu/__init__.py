"""karpenter_core_tpu — a TPU-native cluster-autoscaling framework.

A from-scratch rebuild of the capability set of `karpenter-core` (Kubernetes
node autoscaler, reference mounted at /root/reference): watch unschedulable
pods, evaluate the full Kubernetes scheduling constraint model, bin-pack pods
onto candidate nodes chosen from priced instance-type offerings, launch and
lifecycle those nodes, and continuously deprovision (consolidation, emptiness,
expiration, drift) via scheduling simulation.

Unlike the reference — whose solver is a serial first-fit-decreasing loop in Go
(reference scheduler.go:96-133) — the compute-heavy kernels here encode
pending pods x instance types x topology domains as dense feasibility tensors
and solve provisioning and consolidation replans as vmapped/pjit-sharded JAX
kernels on TPU, behind a pluggable `Solver` interface with an in-process
greedy fallback.

Layer map (mirrors SURVEY.md section 1):
  kube/           k8s-lite object model + in-memory apiserver (envtest analog)
  api/            L0: Provisioner/Machine types, labels, settings
  scheduling/     L1: constraint algebra (requirements, taints, ports, volumes)
  cloudprovider/  L0: SPI, InstanceType/Offering, fake provider
  state/          L2: cluster state cache + informers
  controllers/    L4: provisioning, deprovisioning, machine, node, termination,
                  inflightchecks, counter, metrics
  solver/         snapshot->tensor encoding + Solver interface + gRPC service
  ops/            JAX/Pallas kernels (feasibility, packing, topology, replan)
  parallel/       device mesh, shardings, pjit wrappers
  events/metrics/ observability
  utils/          resource-list algebra and helpers
  analysis/       AST static-analysis passes (hack/lint.py, `make lint`)
  testing/        test fixtures + the lockwatch lock-order race detector
"""

__version__ = "0.1.0"
