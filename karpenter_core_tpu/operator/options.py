"""Process options: CLI flags with env-var fallbacks.

Mirrors reference pkg/operator/options/options.go:30-76 — one place that
defines the process wiring knobs (ports, client QPS/burst, leader election,
memory limit, profiling, webhook toggle). Flags win over env vars; env vars
win over defaults, so the chart's env-based deployment keeps working
unchanged.
"""
from __future__ import annotations

import argparse
from dataclasses import dataclass

from karpenter_core_tpu.obs import envflags

_env = envflags.raw


@dataclass
class Options:
    """options.go:30-45."""

    metrics_port: int = 8000
    health_probe_port: int = 8081
    webhook_port: int = 8443
    kube_client_qps: float = 200.0
    kube_client_burst: int = 300
    enable_leader_election: bool = True
    enable_profiling: bool = False
    disable_webhook: bool = False
    memory_limit: int = -1  # bytes; <=0 -> no GC soft-limit tuning
    log_level: str = "INFO"
    batch_idle_seconds: float = 1.0
    batch_max_seconds: float = 10.0
    solver_endpoint: str = ""

    def apply_memory_limit(self) -> None:
        """The reference sets a GC soft limit at 90% of --memory-limit
        (options.go:72-76 via debug.SetMemoryLimit); the Python analog tunes
        gc thresholds up for large heaps — a no-op unless configured."""
        if self.memory_limit > 0:
            import gc

            gc.set_threshold(50_000, 50, 50)


def parse_options(argv=None) -> Options:
    """Flags > env > defaults (options.go:48-76)."""
    parser = argparse.ArgumentParser(
        prog="karpenter-core-tpu",
        description="karpenter-core-tpu controller process",
    )
    parser.add_argument(
        "--metrics-port", type=int,
        default=int(_env("KARPENTER_METRICS_PORT", "8000")),
    )
    parser.add_argument(
        "--health-probe-port", type=int,
        default=int(_env("KARPENTER_HEALTH_PROBE_PORT", "8081")),
    )
    parser.add_argument(
        "--webhook-port", type=int,
        default=int(_env("KARPENTER_WEBHOOK_PORT", "8443")),
    )
    parser.add_argument(
        "--kube-client-qps", type=float,
        default=float(_env("KARPENTER_KUBE_CLIENT_QPS", "200")),
    )
    parser.add_argument(
        "--kube-client-burst", type=int,
        default=int(_env("KARPENTER_KUBE_CLIENT_BURST", "300")),
    )
    parser.add_argument(
        "--leader-elect", dest="enable_leader_election",
        action=argparse.BooleanOptionalAction,
        default=_env("KARPENTER_LEADER_ELECT", "true").lower() != "false",
    )
    parser.add_argument(
        "--enable-profiling", action="store_true",
        default=_env("KARPENTER_ENABLE_PROFILING", "") == "1",
    )
    parser.add_argument(
        "--disable-webhook", action="store_true",
        default=_env("KARPENTER_DISABLE_WEBHOOK", "") == "1",
    )
    parser.add_argument(
        "--memory-limit", type=int,
        default=int(_env("KARPENTER_MEMORY_LIMIT", "-1")),
    )
    parser.add_argument(
        "--log-level", default=_env("KARPENTER_LOG_LEVEL", "INFO"),
    )
    parser.add_argument(
        "--batch-idle-seconds", type=float,
        default=float(_env("KARPENTER_BATCH_IDLE_SECONDS", "1")),
    )
    parser.add_argument(
        "--batch-max-seconds", type=float,
        default=float(_env("KARPENTER_BATCH_MAX_SECONDS", "10")),
    )
    parser.add_argument(
        "--solver-endpoint", default=_env("KARPENTER_SOLVER_ENDPOINT", ""),
    )
    ns = parser.parse_args(argv)
    return Options(**vars(ns))
