"""Controller-process entrypoint: `python -m karpenter_core_tpu.operator`.

The reference binary is assembled by a vendor embedding NewOperator
(operator.go:68); this standalone entrypoint assembles the same control plane
from environment configuration (the chart's env vars) and serves the health +
metrics endpoints the deployment probes:

  KARPENTER_LOG_LEVEL            python logging level name (default INFO)
  KARPENTER_BATCH_IDLE_SECONDS   provisioning batcher idle window (default 1)
  KARPENTER_BATCH_MAX_SECONDS    provisioning batcher max window (default 10)
  KARPENTER_SOLVER_ENDPOINT      host:port of the gRPC TPU solver; unset ->
                                 in-process TPUSolver (single-process mode)
  KARPENTER_METRICS_PORT         /metrics /healthz /readyz port (default 8000)
  KARPENTER_CHAOS                fault-injection spec (docs/robustness.md);
                                 armed at import, unset in production
  KARPENTER_CHAOS_SEED           default per-point RNG seed for the spec

The karpenter-global-settings ConfigMap, when present in the kube store,
overrides the env defaults (the reference's dynamic-settings path,
settings.go:53-68; env vars are the bootstrap fallback).

A vendor embeds this the same way the reference is embedded: construct a
CloudProvider + kube client (any object with the InMemoryKubeClient surface)
and call run(). Standalone invocation wires the fake provider + in-memory
client — a self-contained control plane useful for smoke tests and chart
validation.
"""
from __future__ import annotations

import json
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from karpenter_core_tpu.api.settings import Settings
from karpenter_core_tpu.metrics.registry import REGISTRY
from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.obs.log import get_logger
from karpenter_core_tpu.operator import new_operator

LOG = get_logger("karpenter.operator")


def solver_from_env():
    """KARPENTER_SOLVER_ENDPOINT -> RemoteSolver, else None (in-process)."""
    endpoint = envflags.raw("KARPENTER_SOLVER_ENDPOINT")
    if not endpoint:
        return None
    from karpenter_core_tpu.solver.service import RemoteSolver

    return RemoteSolver(endpoint)


def settings_from_env() -> Settings:
    return Settings(
        batch_idle_duration=float(envflags.raw("KARPENTER_BATCH_IDLE_SECONDS", "1")),
        batch_max_duration=float(envflags.raw("KARPENTER_BATCH_MAX_SECONDS", "10")),
    )


def resolve_settings(kube_client, options=None) -> Settings:
    """ConfigMap karpenter-global-settings wins over flags/env defaults
    (injection/injection.go:116-127 bootstraps settings from the ConfigMap)."""
    if kube_client is not None:
        for cm in kube_client.list("ConfigMap"):
            if cm.metadata.name == "karpenter-global-settings":
                return Settings.from_config_map(cm.data)
    if options is not None:
        return Settings(
            batch_idle_duration=options.batch_idle_seconds,
            batch_max_duration=options.batch_max_seconds,
        )
    return settings_from_env()


class _ControllerContextFilter:
    """Stamps every record with the context-injected controller name
    (operator/injection.py) so log lines are controller-attributable the
    way the reference's logger.WithValues(controller) lines are."""

    def filter(self, record):
        from karpenter_core_tpu.operator.injection import controller_name

        record.controller = controller_name() or "-"
        return True


def configure_logging() -> None:
    """Arm the package's structured logger (obs/log) from KARPENTER_TPU_LOG
    — on at info by default in the control-plane process, like tracing —
    and keep the legacy stdlib path for vendor libraries:
    KARPENTER_LOGGING_CONFIG (a logging dictConfig JSON, injected from the
    config-logging ConfigMap — the analog of the reference's zap ConfigMap,
    operator.go:95-100) wins; otherwise basicConfig at KARPENTER_LOG_LEVEL.
    Either way, stdlib records carry the injected controller name."""
    import json
    import logging
    import logging.config

    from karpenter_core_tpu.obs.log import configure_logging_from_env

    configure_logging_from_env(default_level="info")
    raw = envflags.raw("KARPENTER_LOGGING_CONFIG")
    configured = False
    if raw:
        try:
            logging.config.dictConfig(json.loads(raw))
            configured = True
        except (ValueError, TypeError, AttributeError, ImportError) as exc:
            LOG.warning(
                "invalid KARPENTER_LOGGING_CONFIG, using basicConfig",
                error_detail=str(exc),
            )
    if not configured:
        level = envflags.raw("KARPENTER_LOG_LEVEL", "INFO").upper()
        logging.basicConfig(
            level=getattr(logging, level, logging.INFO),
            format="%(asctime)s %(levelname)s %(name)s [%(controller)s] %(message)s",
        )
    for handler in logging.getLogger().handlers:
        handler.addFilter(_ControllerContextFilter())


def build_slo_engine(admission=None):
    """The operator's declarative SLOs (obs/slo): admission-to-bind and
    solve-duration latency objectives, evaluated as multi-window burn rates
    per tenant. Registered as an external exposition source so every
    /metrics scrape computes fresh
    karpenter_slo_error_budget_remaining{slo[,tenant]} gauges.

    With *admission* (the live AdmissionGate), a third ratio objective is
    evaluated over the gate's own served/shed accounting
    (``admission_totals``) — the burn signal the brownout ladder consumes,
    so a tenant flooding the gate burns ITS budget even when its requests
    never reach a latency histogram."""
    from karpenter_core_tpu.controllers.provisioning.provisioner import (
        ADMISSION_TO_BIND,
    )
    from karpenter_core_tpu.obs.slo import Objective, SloEngine
    from karpenter_core_tpu.obs.tracer import SOLVER_SOLVE_DURATION

    objectives = [
        Objective(
            name="admission-to-bind",
            histogram=ADMISSION_TO_BIND,
            threshold_s=30.0,
            target=0.99,
            description="99% of pods get a capacity decision within 30s "
                        "of admission",
        ),
        Objective(
            name="solve-duration",
            histogram=SOLVER_SOLVE_DURATION,
            threshold_s=30.0,
            target=0.99,
            base_labels={"context": "provisioning"},
            description="99% of provisioning solves finish inside the 30s "
                        "dispatch deadline",
        ),
    ]
    if admission is not None:
        objectives.append(Objective(
            name="gate-admission",
            histogram=None,
            threshold_s=0.0,
            target=0.95,
            collect=admission.admission_totals,
            description="95% of admission-gate entries dispatch (capacity "
                        "sheds and in-queue deadline expiries burn; ladder "
                        "brownout sheds are excluded so a demoted tenant "
                        "can drain its burn and re-promote)",
        ))
    return SloEngine(objectives)


# every debug endpoint the operator serves: (path, profiling-gated?, what).
# /debug/ renders this as the discovery index; keep it in sync when adding
# endpoints (test_debug_surface checks the handler chain against it).
DEBUG_ENDPOINTS = (
    ("/metrics", False, "Prometheus exposition (openmetrics negotiable)"),
    ("/healthz", False, "liveness probe"),
    ("/readyz", False, "readiness probe"),
    ("/debug/health", False, "solver health: breaker, wedges, abandoned threads"),
    ("/debug/slo", True, "SLO burn rates + error budgets, per tenant"),
    ("/debug/tenants", True, "per-tenant latency/shed/device/compile digest"),
    ("/debug/trace", True, "Chrome trace-event JSON of the solve-path ring"),
    ("/debug/trace/summary", True, "human span summary"),
    ("/debug/timeline", True, "cross-process solve timeline + flight-record index"),
    ("/debug/logs", True, "structured-log ring (logfmt)"),
    ("/debug/logs.json", True, "structured-log ring (JSON)"),
    ("/debug/solves", True, "solve flight-record ring (replayable)"),
    ("/debug/consolidations", True, "consolidation decision ring"),
    ("/debug/events", True, "events recorder ring"),
    ("/debug/threads", True, "all thread stacks (goroutine-dump analog)"),
    ("/debug/backend", True, "device + compile-cache facts"),
    ("/debug/programs", True, "compiled-program cost inventory, all processes"),
    ("/debug/config", True, "context-injected options + settings"),
)


def _debug_index(profiling: bool) -> dict:
    """The /debug/ discovery page: every endpoint, whether it is live in
    this process (profiling-gated endpoints 404 until
    KARPENTER_ENABLE_PROFILING), and what it serves."""
    return {
        "profiling_enabled": profiling,
        "endpoints": [
            {
                "path": path,
                "profiling_gated": gated,
                "enabled": profiling or not gated,
                "description": desc,
            }
            for path, gated, desc in DEBUG_ENDPOINTS
        ],
    }


def _tenants_digest(slo=None) -> dict:
    """The /debug/tenants payload: who burned the budget. Per-tenant
    latency percentiles, shed/fallback breakdowns, device time, compile
    cost, live gate depth, and the flight-record index — read straight off
    the live series the attribution plane labeled (parent process only;
    child series arrive pre-merged in /metrics)."""
    from karpenter_core_tpu.controllers.provisioning.provisioner import (
        ADMISSION_TO_BIND,
    )
    from karpenter_core_tpu.obs.flightrec import FLIGHTREC
    from karpenter_core_tpu.obs.reqctx import TENANTS
    from karpenter_core_tpu.obs.tracer import (
        SOLVER_PHASE_DURATION,
        SOLVER_SOLVE_DURATION,
    )
    from karpenter_core_tpu.solver.fallback import SOLVER_FALLBACK_TOTAL
    from karpenter_core_tpu.solver.host import (
        DEADLINE_VIOLATIONS_TOTAL,
        GATE_DEMOTIONS_TOTAL,
        SOLVER_QUEUE_DEPTH,
        SOLVER_QUEUE_WAIT,
        SOLVER_SHED_TOTAL,
    )
    from karpenter_core_tpu.utils.compilecache import (
        CACHE_MISSES,
        COMPILE_SECONDS,
    )

    tenants: dict = {}

    def entry(tenant: str) -> dict:
        return tenants.setdefault(tenant, {
            "admission_to_bind_s": {},
            "solve_duration_s": {},
            "queue_wait_s": {},
            "shed": {},
            "fallback": {},
            "device_ms": 0.0,
            "compile_misses": 0,
            "compile_seconds": 0.0,
            "gate_depth": {},
            "expired_in_queue": 0,
            "demotions": {},
            "flight_records": [],
        })

    def percentiles(hist, labels, data):
        return {
            "count": int(data["count"]),
            "p50": hist.percentile(0.50, labels),
            "p99": hist.percentile(0.99, labels),
        }

    for labels, data in ADMISSION_TO_BIND.series():
        t = labels.get("tenant")
        if t is not None:
            entry(t)["admission_to_bind_s"] = percentiles(
                ADMISSION_TO_BIND, labels, data
            )
    for labels, data in SOLVER_SOLVE_DURATION.series():
        t = labels.get("tenant")
        if t is not None:
            entry(t)["solve_duration_s"] = percentiles(
                SOLVER_SOLVE_DURATION, labels, data
            )
    for labels, data in SOLVER_QUEUE_WAIT.series():
        t = labels.get("tenant")
        if t is not None:
            entry(t)["queue_wait_s"] = percentiles(
                SOLVER_QUEUE_WAIT, labels, data
            )
    for labels, value in SOLVER_SHED_TOTAL.series():
        t = labels.get("tenant")
        if t is not None:
            shed = entry(t)["shed"]
            reason = labels.get("reason", "")
            shed[reason] = shed.get(reason, 0) + int(value)
    for labels, value in SOLVER_FALLBACK_TOTAL.series():
        t = labels.get("tenant")
        if t is not None:
            fb = entry(t)["fallback"]
            reason = labels.get("reason", "")
            fb[reason] = fb.get(reason, 0) + int(value)
    for labels, value in DEADLINE_VIOLATIONS_TOTAL.series():
        t = labels.get("tenant")
        # stage=queue: requests that expired while waiting and were shed,
        # attributed to the tenant that overran its budget (ISSUE 17)
        if t is not None and labels.get("stage") == "queue":
            entry(t)["expired_in_queue"] += int(value)
    for labels, value in GATE_DEMOTIONS_TOTAL.series():
        t = labels.get("tenant")
        if t is not None:
            dem = entry(t)["demotions"]
            reason = labels.get("reason", "")
            dem[reason] = dem.get(reason, 0) + int(value)
    for labels, data in SOLVER_PHASE_DURATION.series():
        t = labels.get("tenant")
        if t is not None and labels.get("phase") == "device":
            entry(t)["device_ms"] += round(float(data["sum"]) * 1e3, 1)
    for labels, value in CACHE_MISSES.series():
        t = labels.get("tenant")
        if t is not None:
            entry(t)["compile_misses"] += int(value)
    for labels, data in COMPILE_SECONDS.series():
        t = labels.get("tenant")
        if t is not None:
            entry(t)["compile_seconds"] += round(float(data["sum"]), 3)
    for labels, value in list(SOLVER_QUEUE_DEPTH.values.items()):
        d = dict(labels)
        t = d.get("tenant")
        if t is not None:
            entry(t)["gate_depth"][d.get("gate", "")] = value
    for tenant, records in FLIGHTREC.tenant_index().items():
        if tenant:
            entry(tenant)["flight_records"] = records
    digest = {"guard": TENANTS.stats(), "tenants": tenants}
    if slo is not None:
        digest["budget_exhausted"] = sorted(
            t for t in tenants if slo.budget_exhausted(t)
        )
    return digest


def _debug_threads() -> str:
    """All thread stacks — the goroutine-dump analog of the reference's
    pprof handlers (operator/profiling.go:25), for diagnosing stuck loops."""
    import sys
    import traceback

    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _debug_backend() -> str:
    """Device + compile-cache facts for the solver process."""
    try:
        import jax

        dev = jax.devices()[0]
        info = {
            "platform": dev.platform,
            "device_kind": dev.device_kind,
            "device_count": len(jax.devices()),
            "live_arrays": len(jax.live_arrays()),
        }
    except Exception as exc:  # backend may be unavailable; report, don't die
        info = {"error": f"{type(exc).__name__}: {exc}"}
    return json.dumps(info) + "\n"


class _HealthHandler(BaseHTTPRequestHandler):
    operator = None  # set by serve_health
    solver = None  # the ResilientSolver, when the wiring passes it
    slo = None  # the SloEngine, when the wiring passes it
    profiling_enabled = False  # set from KARPENTER_ENABLE_PROFILING

    def do_GET(self):
        if self.path == "/metrics":
            # exemplars (trace-id links on histogram buckets, ISSUE 15)
            # are only legal under the negotiated OpenMetrics type — the
            # 0.0.4 parser reads the suffix as a malformed timestamp and
            # fails the WHOLE scrape, so the plain exposition never
            # carries them
            accept = self.headers.get("Accept", "")
            if "application/openmetrics-text" in accept:
                body = (
                    REGISTRY.expose(exemplars=True).encode() + b"\n# EOF\n"
                )
                ctype = (
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8"
                )
            else:
                body = REGISTRY.expose().encode() + b"\n"
                ctype = "text/plain; version=0.0.4"
        elif self.path == "/debug/health":
            # wedge observability (ISSUE 11): dispatch heartbeat age,
            # breaker state, wedge history, abandoned-thread inventory.
            # Deliberately NOT profiling-gated — this is the first thing
            # an operator curls when provisioning degrades.
            report = None
            solver = self.solver
            if solver is not None and hasattr(solver, "health_report"):
                report = solver.health_report()
            status = "ok"
            if report is not None and report.get("healthy") is False:
                status = "degraded"
            body = json.dumps(
                {"status": status, "solver": report}, sort_keys=True
            ).encode() + b"\n"
            ctype = "application/json"
        elif self.path in ("/debug", "/debug/"):
            # the discovery index (ISSUE 16): ungated, so an operator can
            # always enumerate what this process serves — gated endpoints
            # are listed with enabled=false rather than hidden
            body = json.dumps(
                _debug_index(self.profiling_enabled), sort_keys=True
            ).encode() + b"\n"
            ctype = "application/json"
        elif self.path == "/debug/slo" and self.profiling_enabled:
            # burn rates + error budgets per objective and tenant
            if self.slo is not None:
                payload = self.slo.digest()
            else:
                payload = {"error": "slo engine not wired"}
            body = json.dumps(payload, sort_keys=True).encode() + b"\n"
            ctype = "application/json"
        elif self.path == "/debug/tenants" and self.profiling_enabled:
            # who burned the budget: the per-tenant cost/latency digest
            body = json.dumps(
                _tenants_digest(self.slo), sort_keys=True
            ).encode() + b"\n"
            ctype = "application/json"
        elif self.path == "/debug/trace" and self.profiling_enabled:
            # Chrome trace-event JSON of the solve-path ring buffer: save
            # and load in Perfetto (ui.perfetto.dev) or chrome://tracing
            from karpenter_core_tpu.obs import TRACER

            body = json.dumps(TRACER.chrome_trace()).encode()
            ctype = "application/json"
        elif self.path == "/debug/trace/summary" and self.profiling_enabled:
            from karpenter_core_tpu.obs import TRACER

            body = TRACER.summary().encode() + b"\n"
            ctype = "text/plain"
        elif self.path == "/debug/timeline" and self.profiling_enabled:
            # the cross-process solve timeline (ISSUE 15): the same
            # Perfetto-loadable trace as /debug/trace — grafted solver-host
            # child spans on their own pid track, kill/respawn/breaker
            # instant markers — PLUS the trace-id -> flight-record index,
            # so a span on the timeline links straight to the replayable
            # inputs of the solve it belongs to
            from karpenter_core_tpu.obs import TRACER
            from karpenter_core_tpu.obs.flightrec import FLIGHTREC

            timeline = TRACER.chrome_trace()
            timeline["otherData"]["flight_records"] = {
                r["trace_id"]: r.get("digest", "")
                for r in FLIGHTREC.records() if r.get("trace_id")
            }
            body = json.dumps(timeline).encode()
            ctype = "application/json"
        elif self.path == "/debug/logs" and self.profiling_enabled:
            # the structured-log ring (obs/log): logfmt lines, trace ids
            # joining /debug/trace spans
            from karpenter_core_tpu.obs.log import SINK

            body = SINK.lines().encode()
            ctype = "text/plain"
        elif self.path == "/debug/logs.json" and self.profiling_enabled:
            from karpenter_core_tpu.obs.log import SINK, format_json

            body = ("[" + ",".join(
                format_json(r) for r in SINK.records()
            ) + "]").encode()
            ctype = "application/json"
        elif self.path == "/debug/solves" and self.profiling_enabled:
            # the solve flight-record ring (obs/flightrec): download, then
            # `python hack/replay.py` any record offline
            from karpenter_core_tpu.obs.flightrec import FLIGHTREC

            body = FLIGHTREC.to_json().encode()
            ctype = "application/json"
        elif self.path == "/debug/consolidations" and self.profiling_enabled:
            # consolidation decision ring (obs/flightrec): candidate set +
            # screened subsets + chosen Command per deprovisioning pass;
            # `python hack/replay.py --consolidation` diffs any record
            # against the sequential simulator offline
            from karpenter_core_tpu.obs.flightrec import FLIGHTREC

            body = FLIGHTREC.consolidations_json().encode()
            ctype = "application/json"
        elif self.path == "/debug/events" and self.profiling_enabled:
            # the events Recorder ring (events/__init__), dedupe/rate-limit
            # metadata included
            recorder = getattr(self.operator, "recorder", None)
            body = json.dumps(
                recorder.export() if recorder is not None else []
            ).encode()
            ctype = "application/json"
        elif self.path in ("/healthz", "/readyz"):
            body = json.dumps({"status": "ok"}).encode()
            ctype = "application/json"
        elif self.path == "/debug/threads" and self.profiling_enabled:
            body = _debug_threads().encode()
            ctype = "text/plain"
        elif self.path == "/debug/backend" and self.profiling_enabled:
            body = _debug_backend().encode()
            ctype = "application/json"
        elif self.path == "/debug/programs" and self.profiling_enabled:
            # the unified compiled-program cost inventory (ISSUE 18): the
            # local ledger plus every registered source — in host mode the
            # sidecar child's programs arrive via the stats/response-frame
            # snapshots and surface here under process="solver-host"
            from karpenter_core_tpu.obs import proghealth

            body = json.dumps(
                proghealth.full_snapshot(), sort_keys=True
            ).encode()
            ctype = "application/json"
        elif self.path == "/debug/config" and self.profiling_enabled:
            # context-injected config (operator/injection.py)
            from dataclasses import asdict, is_dataclass

            from karpenter_core_tpu.operator import injection

            opts = injection.get_options()
            settings = injection.get_settings()
            body = json.dumps(
                {
                    "options": asdict(opts) if is_dataclass(opts) else repr(opts),
                    "settings": asdict(settings)
                    if is_dataclass(settings)
                    else repr(settings),
                }
            ).encode()
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet probe spam
        pass


def serve_health(operator, port: int, profiling: bool = False,
                 solver=None, slo=None) -> ThreadingHTTPServer:
    _HealthHandler.operator = operator
    _HealthHandler.solver = solver
    _HealthHandler.slo = slo
    # opt-in debug handlers, like the reference's --enable-profiling pprof
    # registration (operator.go:124-126)
    _HealthHandler.profiling_enabled = profiling
    server = ThreadingHTTPServer(("0.0.0.0", port), _HealthHandler)
    threading.Thread(
        target=server.serve_forever, daemon=True, name="health-metrics-http"
    ).start()
    return server


def run(cloud_provider, kube_client=None, stop_event=None, options=None):
    """Assemble and run the control plane until stop_event (or a signal).

    Settings resolve from the client's karpenter-global-settings ConfigMap
    when the embedding vendor passes an API-backed client; the standalone
    in-memory client has no ConfigMap, so flags/env apply. With leader
    election enabled (the default, operator.go:108-110) the controllers only
    start once the lease is held, and losing it stops the process."""
    from karpenter_core_tpu.operator.options import parse_options

    # embedded path: resolve env vars through the same flag layer as the CLI
    # (flags > env > defaults), so KARPENTER_* documented above keep working
    opts = options or parse_options([])
    configure_logging()
    opts.apply_memory_limit()
    # solve-path tracing is ON in the production control plane (the whole
    # point of ISSUE 1: perf work starts from data, not guesses); its
    # enabled-path cost is a handful of span objects per reconcile.
    # KARPENTER_TPU_TRACE=0/false/off opts out for perf-pathological
    # deployments.
    from karpenter_core_tpu.obs import enable_tracing_from_env

    enable_tracing_from_env(default_on=True)
    # the solve flight recorder is ON in the production control plane for
    # the same reason tracing is: a bad placement is only debuggable if
    # its exact inputs were captured. KARPENTER_TPU_FLIGHTREC=0 opts out.
    from karpenter_core_tpu.obs import enable_flightrec_from_env

    enable_flightrec_from_env(default_on=True)
    # restart-survivable compiled programs: a rebooted control plane must
    # not blank provisioning for the cold-compile window (utils/compilecache)
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    enable_persistent_cache()
    if kube_client is None:
        from karpenter_core_tpu.kube.client import InMemoryKubeClient

        kube_client = InMemoryKubeClient()
    if opts.solver_endpoint:
        from karpenter_core_tpu.solver.service import RemoteSolver

        primary = RemoteSolver(opts.solver_endpoint)
    else:
        primary = solver_from_env()
        if primary is None:
            # the hard-killable solver host (solver/host.py, ISSUE 12) is
            # the operator DEFAULT: the device dispatch runs in a
            # supervised sidecar the watchdog can SIGKILL on a wedge, so
            # one hung XLA call never poisons this process.
            # KARPENTER_SOLVER_HOST=off restores the in-process path
            # (mesh autodetection: >1 visible device -> ShardedSolver,
            # else TPUSolver — solver/factory.py).
            from karpenter_core_tpu.solver.factory import (
                build_primary,
                describe,
                host_mode_enabled,
            )

            primary = build_primary(host_default=True)
            if host_mode_enabled(True):
                LOG.info("solver host enabled", solver="HostSolver")
            else:
                LOG.info("in-process solver", solver=describe(primary))
    # production backend-failure defense: subprocess-probe the accelerator,
    # route solves to the host greedy path while it is wedged/unavailable,
    # re-probe for recovery (solver/fallback.py)
    from karpenter_core_tpu.solver.fallback import ResilientSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver

    # wedge detection rides the dispatch watchdog: the solver's phase marks
    # touch a heartbeat; 600s of silence (longer than any prewarmed-path
    # compile) is a wedge — abandoned early, breaker open, re-admission
    # gated by the out-of-band probe (solver/fallback.py, ISSUE 11).
    # IN-PROCESS primaries only: a RemoteSolver's dispatch blocks in one
    # RPC with no client-side phase marks, so heartbeat staleness would
    # misread every long remote solve as a wedge — the remote deployment's
    # wedge detection lives SERVER-side (the service's per-RPC dispatch
    # heartbeats + the Health RPC's wedged status, which the prober sees).
    is_remote = callable(getattr(primary, "health", None))
    solver = ResilientSolver(
        primary, GreedySolver(), solve_timeout=900.0,
        wedge_stale_after=None if is_remote else 600.0,
    )
    settings = resolve_settings(kube_client, opts)
    # context-carried config bootstrap (injection.go:116-127)
    from karpenter_core_tpu.operator.injection import inject_defaults

    inject_defaults(options=opts, settings=settings)
    operator = new_operator(
        cloud_provider,
        kube_client=kube_client,
        settings=settings,
        solver=solver,
        with_webhooks=not opts.disable_webhook,
    )
    solver.recorder = operator.recorder
    # the wrapper IS the fallback layer: point the provisioner's own
    # fallback at it so the two mechanisms don't stack
    operator.provisioning.fallback_solver = solver
    # long-lived-server GC posture (utils/gctuning.py): freeze the wired-up
    # baseline out of collector scans so gen-2 pauses don't land mid-Solve
    # (the CPython analog of the reference's --memory-limit GOGC tuning,
    # operator.go:84-88). The bench applies the same call after its warmup.
    from karpenter_core_tpu.utils.gctuning import apply_server_gc_tuning

    apply_server_gc_tuning()
    # the SLO burn-rate plane (ISSUE 16): declarative objectives over the
    # histograms the attribution plane labels, exposed as fresh-per-scrape
    # error-budget gauges and the /debug/slo digest — plus (ISSUE 17) a
    # ratio objective over the admission gate's own served/shed accounting
    gate = getattr(primary, "admission", None)
    slo_engine = build_slo_engine(admission=gate)
    REGISTRY.add_external(slo_engine)
    # compiled-program cost families (ISSUE 18): every scrape summarizes
    # the unified inventory (local ledger + solver-host merger) into
    # karpenter_program_{count,compile_seconds_total,hbm_peak_bytes}
    from karpenter_core_tpu.obs import proghealth

    proghealth.ensure_exposition_registered()
    # KARPENTER_SLO_BROWNOUT arms the closed SLO->admission loop:
    #   * the depth-band preference: inside the brownout band the gate
    #     sheds ONLY tenants whose error budget is exhausted (fast-burning
    #     tenants pay first), instead of shedding everyone;
    #   * the per-tenant brownout ladder: a tenant whose fast-window burn
    #     crosses the threshold is demoted device -> greedy -> shed (with
    #     hysteresis) while every other tenant keeps the device path.
    if envflags.get_bool("KARPENTER_SLO_BROWNOUT", False):
        if gate is not None:
            from karpenter_core_tpu.solver.host import BrownoutLadder

            gate.brownout_prefer = slo_engine.budget_exhausted
            gate.ladder = BrownoutLadder(burn=slo_engine.fast_burn)
            LOG.info("slo brownout loop armed", gate=gate.name)
    health = serve_health(
        operator, opts.metrics_port, profiling=opts.enable_profiling,
        solver=solver, slo=slo_engine,
    )
    stop = stop_event or threading.Event()
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: stop.set())
    except ValueError:
        pass  # not the main thread (embedded/test use)

    # startup AOT prewarm (solver/prewarm.py): compile the bucket ladder's
    # solve/prescreen/refresh programs on a background thread, overlapped
    # with the watch-cache sync, so the FIRST Solve() after a restart lands
    # on an already-compiled (or persistent-cache-deserialized) program
    # instead of paying the cold compile. KARPENTER_PREWARM=0 opts out;
    # KARPENTER_PREWARM_TIERS=S,M restricts the rungs.
    if envflags.get_bool("KARPENTER_PREWARM", True):
        from karpenter_core_tpu.solver.prewarm import start_prewarm_thread

        tier_env = envflags.raw("KARPENTER_PREWARM_TIERS")
        start_prewarm_thread(
            primary,
            provisioners_fn=lambda: kube_client.list("Provisioner"),
            instance_types_fn=lambda provs: {
                p.name: cloud_provider.get_instance_types(p) for p in provs
            },
            settings=settings,
            tiers=(
                [t.strip() for t in tier_env.split(",") if t.strip()]
                if tier_env
                else None
            ),
            stop=stop,
        )

    elector = None
    if opts.enable_leader_election:
        from karpenter_core_tpu.operator.leaderelection import LeaderElector

        elector = LeaderElector(kube_client)
        if not elector.acquire_blocking(stop):
            health.shutdown()
            return operator  # stopped before leadership
        elector.start_renewing(stop)
    # HTTPS admission endpoint over the same in-process admission brain
    # (webhooks.go:17-63). Started AFTER leader election so only the leader
    # rotates the shared cert Secret; any startup failure degrades to
    # in-process admission instead of killing the controller.
    webhook_server = None
    if not opts.disable_webhook and opts.webhook_port:
        from karpenter_core_tpu.webhooks.server import WebhookServer

        webhook_server = WebhookServer(
            operator.kube_client, host="0.0.0.0", port=opts.webhook_port
        )
        try:
            webhook_server.start()
        except Exception as exc:  # port conflict, apiserver 4xx, cert race
            LOG.warning("webhook server disabled", error_detail=str(exc))
            webhook_server = None
    operator.start()
    LOG.info("controller running", metrics_port=opts.metrics_port)
    stop.wait()
    operator.stop()
    if elector is not None:
        elector.release()
    if webhook_server is not None:
        webhook_server.stop()
    health.shutdown()
    return operator


def main():
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_core_tpu.operator.options import parse_options

    run(FakeCloudProvider(), options=parse_options())


if __name__ == "__main__":
    main()
