"""Controller-process entrypoint: `python -m karpenter_core_tpu.operator`.

The reference binary is assembled by a vendor embedding NewOperator
(operator.go:68); this standalone entrypoint assembles the same control plane
from environment configuration (the chart's env vars) and serves the health +
metrics endpoints the deployment probes:

  KARPENTER_LOG_LEVEL            python logging level name (default INFO)
  KARPENTER_BATCH_IDLE_SECONDS   provisioning batcher idle window (default 1)
  KARPENTER_BATCH_MAX_SECONDS    provisioning batcher max window (default 10)
  KARPENTER_SOLVER_ENDPOINT      host:port of the gRPC TPU solver; unset ->
                                 in-process TPUSolver (single-process mode)
  KARPENTER_METRICS_PORT         /metrics /healthz /readyz port (default 8000)

The karpenter-global-settings ConfigMap, when present in the kube store,
overrides the env defaults (the reference's dynamic-settings path,
settings.go:53-68; env vars are the bootstrap fallback).

A vendor embeds this the same way the reference is embedded: construct a
CloudProvider + kube client (any object with the InMemoryKubeClient surface)
and call run(). Standalone invocation wires the fake provider + in-memory
client — a self-contained control plane useful for smoke tests and chart
validation.
"""
from __future__ import annotations

import json
import os
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from karpenter_core_tpu.api.settings import Settings
from karpenter_core_tpu.metrics.registry import REGISTRY
from karpenter_core_tpu.operator import new_operator


def solver_from_env():
    """KARPENTER_SOLVER_ENDPOINT -> RemoteSolver, else None (in-process)."""
    endpoint = os.environ.get("KARPENTER_SOLVER_ENDPOINT", "")
    if not endpoint:
        return None
    from karpenter_core_tpu.solver.service import RemoteSolver

    return RemoteSolver(endpoint)


def settings_from_env() -> Settings:
    return Settings(
        batch_idle_duration=float(os.environ.get("KARPENTER_BATCH_IDLE_SECONDS", "1")),
        batch_max_duration=float(os.environ.get("KARPENTER_BATCH_MAX_SECONDS", "10")),
    )


def resolve_settings(kube_client) -> Settings:
    """ConfigMap karpenter-global-settings wins over env defaults
    (injection/injection.go:116-127 bootstraps settings from the ConfigMap)."""
    if kube_client is not None:
        for cm in kube_client.list("ConfigMap"):
            if cm.metadata.name == "karpenter-global-settings":
                return Settings.from_config_map(cm.data)
    return settings_from_env()


def configure_logging() -> None:
    import logging

    level = os.environ.get("KARPENTER_LOG_LEVEL", "INFO").upper()
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )


class _HealthHandler(BaseHTTPRequestHandler):
    operator = None  # set by serve_health

    def do_GET(self):
        if self.path == "/metrics":
            body = REGISTRY.expose().encode() + b"\n"
            ctype = "text/plain; version=0.0.4"
        elif self.path in ("/healthz", "/readyz"):
            body = json.dumps({"status": "ok"}).encode()
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet probe spam
        pass


def serve_health(operator, port: int) -> ThreadingHTTPServer:
    _HealthHandler.operator = operator
    server = ThreadingHTTPServer(("0.0.0.0", port), _HealthHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def run(cloud_provider, kube_client=None, stop_event=None):
    """Assemble and run the control plane until stop_event (or a signal).

    Settings resolve from the client's karpenter-global-settings ConfigMap
    when the embedding vendor passes an API-backed client; the standalone
    in-memory client has no ConfigMap, so env vars apply."""
    configure_logging()
    if kube_client is None:
        from karpenter_core_tpu.kube.client import InMemoryKubeClient

        kube_client = InMemoryKubeClient()
    operator = new_operator(
        cloud_provider,
        kube_client=kube_client,
        settings=resolve_settings(kube_client),
        solver=solver_from_env(),
        with_webhooks=True,
    )
    port = int(os.environ.get("KARPENTER_METRICS_PORT", "8000"))
    health = serve_health(operator, port)
    operator.start()
    print(f"controller running; health/metrics on :{port}", flush=True)

    stop = stop_event or threading.Event()
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: stop.set())
    except ValueError:
        pass  # not the main thread (embedded/test use)
    stop.wait()
    operator.stop()
    health.shutdown()
    return operator


def main():
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider

    run(FakeCloudProvider())


if __name__ == "__main__":
    main()
