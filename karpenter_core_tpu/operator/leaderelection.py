"""Lease-based leader election.

Mirrors reference operator.go:108-110 (controller-runtime's
LeaderElectionResourceLock "leases", id "karpenter-leader-election"): the
control plane only runs while holding a coordination.k8s.io/v1 Lease; a
standby acquires it when the holder's renew deadline lapses. Both
transitions are compare-and-swap shaped against the apiserver's 409
contract, so two processes sharing an API-backed client arbitrate
correctly; the in-memory single-process client acquires trivially.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

LEASE_NAME = "karpenter-leader-election"
LEASE_NAMESPACE = "kube-system"
LEASE_DURATION = 15.0  # controller-runtime defaults
RENEW_PERIOD = 2.0
RETRY_PERIOD = 2.0


class LeaderElector:
    def __init__(self, kube_client, identity: Optional[str] = None,
                 clock=time.time, lease_duration: float = LEASE_DURATION):
        self.kube_client = kube_client
        self.identity = identity or f"karpenter-{uuid.uuid4().hex[:8]}"
        self.clock = clock
        self.lease_duration = lease_duration
        self._renew_thread: Optional[threading.Thread] = None

    def _lease(self):
        return self.kube_client.get("Lease", LEASE_NAMESPACE, LEASE_NAME)

    def try_acquire(self) -> bool:
        """Acquire (or re-acquire) the lease if free or expired.

        Both transitions are compare-and-swap shaped so two standbys racing
        for an expired lease cannot both win: creation loses to
        AlreadyExists (another process created first) and takeover goes
        through compare_and_update against the observed resource_version
        (the apiserver's 409 contract); a conflict means someone else
        renewed or took the lease first, so this attempt simply fails and
        the caller retries."""
        from karpenter_core_tpu.kube.objects import Lease, LeaseSpec, ObjectMeta

        now = self.clock()
        lease = self._lease()
        if lease is None:
            lease = Lease(
                metadata=ObjectMeta(name=LEASE_NAME, namespace=LEASE_NAMESPACE),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    lease_duration_seconds=int(self.lease_duration),
                    acquire_time=now,
                    renew_time=now,
                ),
            )
            try:
                self.kube_client.create(lease)
            except Exception:  # AlreadyExists: lost the create race
                return False
            return True
        holder = lease.spec.holder_identity
        renew_time = lease.spec.renew_time or 0.0
        if holder == self.identity or now - renew_time > self.lease_duration:
            observed_rv = lease.metadata.resource_version
            if holder != self.identity:  # takeover, not renewal
                lease.spec.acquire_time = now
                lease.spec.lease_transitions += 1
            lease.spec.holder_identity = self.identity
            lease.spec.renew_time = now
            cas = getattr(self.kube_client, "compare_and_update", None)
            try:
                if cas is not None:
                    cas(lease, observed_rv)
                else:
                    self.kube_client.update(lease)
            except Exception:  # conflict: another process moved first
                return False
            return True
        return False

    def acquire_blocking(self, stop: threading.Event) -> bool:
        """Block until the lease is held or stop is set. Returns held."""
        while not stop.is_set():
            if self.try_acquire():
                return True
            stop.wait(RETRY_PERIOD)
        return False

    def start_renewing(self, stop: threading.Event) -> None:
        def renew():
            while not stop.is_set():
                stop.wait(RENEW_PERIOD)
                if not self.try_acquire():  # lost the lease: stop the plane
                    stop.set()
                    return

        self._renew_thread = threading.Thread(
            target=renew, name="leader-election-renew", daemon=True
        )
        self._renew_thread.start()

    def release(self) -> None:
        """Clear the renew time so a standby can take over immediately
        (graceful handoff on shutdown). None (not 0.0) so the field is
        simply omitted on the wire — a real apiserver rejects non-RFC3339
        MicroTime values."""
        lease = self._lease()
        if lease is not None and lease.spec.holder_identity == self.identity:
            lease.spec.renew_time = None
            self.kube_client.update(lease)
