"""Lease-based leader election.

Mirrors reference operator.go:108-110 (controller-runtime's
LeaderElectionResourceLock "leases", id "karpenter-leader-election"): the
control plane only runs while holding the lease; a standby acquires it when
the holder's renew deadline lapses. The lease record is a ConfigMap-shaped
object in the kube store, so two processes sharing an API-backed client
arbitrate correctly; the in-memory single-process client acquires trivially.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

LEASE_NAME = "karpenter-leader-election"
LEASE_NAMESPACE = "kube-system"
LEASE_DURATION = 15.0  # controller-runtime defaults
RENEW_PERIOD = 2.0
RETRY_PERIOD = 2.0


class LeaderElector:
    def __init__(self, kube_client, identity: Optional[str] = None,
                 clock=time.time, lease_duration: float = LEASE_DURATION):
        self.kube_client = kube_client
        self.identity = identity or f"karpenter-{uuid.uuid4().hex[:8]}"
        self.clock = clock
        self.lease_duration = lease_duration
        self._renew_thread: Optional[threading.Thread] = None

    def _lease(self):
        return self.kube_client.get("ConfigMap", LEASE_NAMESPACE, LEASE_NAME)

    def try_acquire(self) -> bool:
        """Acquire (or re-acquire) the lease if free or expired.

        Both transitions are compare-and-swap shaped so two standbys racing
        for an expired lease cannot both win: creation loses to
        AlreadyExists (another process created first) and takeover goes
        through compare_and_update against the observed resource_version
        (the apiserver's 409 contract); a conflict means someone else
        renewed or took the lease first, so this attempt simply fails and
        the caller retries."""
        now = self.clock()
        lease = self._lease()
        if lease is None:
            from karpenter_core_tpu.kube.objects import ConfigMap, ObjectMeta

            lease = ConfigMap(
                metadata=ObjectMeta(name=LEASE_NAME, namespace=LEASE_NAMESPACE),
                data={"holder": self.identity, "renew_time": str(now)},
            )
            try:
                self.kube_client.create(lease)
            except Exception:  # AlreadyExists: lost the create race
                return False
            return True
        holder = lease.data.get("holder", "")
        renew_time = float(lease.data.get("renew_time", "0"))
        if holder == self.identity or now - renew_time > self.lease_duration:
            observed_rv = lease.metadata.resource_version
            lease.data["holder"] = self.identity
            lease.data["renew_time"] = str(now)
            cas = getattr(self.kube_client, "compare_and_update", None)
            try:
                if cas is not None:
                    cas(lease, observed_rv)
                else:
                    self.kube_client.update(lease)
            except Exception:  # conflict: another process moved first
                return False
            return True
        return False

    def acquire_blocking(self, stop: threading.Event) -> bool:
        """Block until the lease is held or stop is set. Returns held."""
        while not stop.is_set():
            if self.try_acquire():
                return True
            stop.wait(RETRY_PERIOD)
        return False

    def start_renewing(self, stop: threading.Event) -> None:
        def renew():
            while not stop.is_set():
                stop.wait(RENEW_PERIOD)
                if not self.try_acquire():  # lost the lease: stop the plane
                    stop.set()
                    return

        self._renew_thread = threading.Thread(
            target=renew, name="leader-election-renew", daemon=True
        )
        self._renew_thread.start()

    def release(self) -> None:
        lease = self._lease()
        if lease is not None and lease.data.get("holder") == self.identity:
            lease.data["renew_time"] = "0"
            self.kube_client.update(lease)
