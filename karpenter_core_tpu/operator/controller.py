"""Controller runtime abstraction: rate-limited singleton loops with
per-reconcile instrumentation.

Mirrors reference pkg/operator/controller/singleton.go:58-129 — each
singleton controller runs its own loop with a rate limiter, records a
duration histogram and error counter per reconcile, and backs off
exponentially on failure instead of spinning. Round 1's raw threads caught
and DISCARDED every exception (VERDICT weak #8); this module is the
replacement.
"""
from __future__ import annotations

import itertools
import queue
import random
import threading
import time
from typing import Callable, Optional

from karpenter_core_tpu.metrics.registry import REGISTRY
from karpenter_core_tpu.obs import TRACER
from karpenter_core_tpu.obs.log import bound as log_bound, get_logger
from karpenter_core_tpu.operator.injection import with_controller_name

LOG = get_logger("karpenter.controller")

# process-wide reconcile ids: every log line inside one reconcile carries the
# same reconcile=rNNN field (the request-id analog of the reference's
# controller-runtime request logging), so a failing pass greps as a unit
_reconcile_ids = itertools.count(1)

RECONCILE_DURATION = REGISTRY.histogram(
    "karpenter_controller_reconcile_duration_seconds",
    "Duration of controller reconcile loops (singleton.go:66-78)",
)
RECONCILE_ERRORS = REGISTRY.counter(
    "karpenter_controller_reconcile_errors_total",
    "Reconcile invocations that raised (singleton.go:84-90)",
)

# workqueue.DefaultItemBasedRateLimiter shape: 5ms base, 10s cap
ERROR_BACKOFF_BASE = 0.005
ERROR_BACKOFF_MAX = 10.0


class Singleton:
    """A self-clocked reconcile loop (singleton.go:92-129).

    reconcile() may return a requeue-after interval in seconds (None ->
    the default interval). Exceptions are logged, counted, and backed off
    exponentially; they never kill the loop silently."""

    def __init__(
        self,
        name: str,
        reconcile: Callable[[], Optional[float]],
        interval: float = 1.0,
        clock=time.time,
        rng: Optional[random.Random] = None,
    ):
        self.name = name
        self.reconcile = reconcile
        self.interval = interval
        self.clock = clock
        self._failures = 0
        self._rng = rng or random.Random()
        # decorrelated-jitter state: last backoff actually slept
        self._last_backoff = ERROR_BACKOFF_BASE
        self._thread: Optional[threading.Thread] = None

    def reconcile_once(self) -> Optional[float]:
        """One instrumented reconcile; returns the wait before the next."""
        start = time.perf_counter()
        # allocated OUTSIDE the bound scope so the failure line below (the
        # one record that explains a pass) carries the same reconcile id as
        # the pass's in-scope lines
        reconcile_id = f"r{next(_reconcile_ids)}"
        try:
            # spans nest: a provisioning reconcile's solve phases land under
            # this root in the exported trace. RECONCILE_DURATION is observed
            # in the finally below (always on), so the tracer's metrics
            # bridge deliberately skips controller.reconcile spans. The log
            # binding stamps every line emitted below (any depth) with the
            # controller + reconcile id, correlating logs across the pass.
            with with_controller_name(self.name), log_bound(
                controller=self.name, reconcile=reconcile_id
            ), TRACER.span("controller.reconcile", controller=self.name):
                requeue_after = self.reconcile()
        except Exception:
            RECONCILE_ERRORS.inc(labels={"controller": self.name})
            self._failures += 1
            # decorrelated jitter (utils/backoff; the run-loop jitter
            # hook's shape, operator/__init__.py): sleep ~ U(base,
            # 3 * last_sleep), capped. N controllers failing on the same
            # dead apiserver spread out instead of thundering-herding it in
            # lockstep every 10s — and the expected sleep still grows
            # geometrically like the old pure-exponential ladder.
            from karpenter_core_tpu.utils.backoff import decorrelated_jitter

            backoff = decorrelated_jitter(
                self._last_backoff, ERROR_BACKOFF_BASE, ERROR_BACKOFF_MAX,
                self._rng,
            )
            self._last_backoff = max(backoff, ERROR_BACKOFF_BASE)
            LOG.exception(
                "reconcile failed", controller=self.name,
                reconcile=reconcile_id, failures=self._failures,
                backoff_s=round(backoff, 3),
            )
            return backoff
        finally:
            RECONCILE_DURATION.observe(
                time.perf_counter() - start, labels={"controller": self.name}
            )
        self._failures = 0
        self._last_backoff = ERROR_BACKOFF_BASE
        return self.interval if requeue_after is None else requeue_after

    def start(self, stop: threading.Event) -> threading.Thread:
        def loop():
            while not stop.is_set():
                wait = self.reconcile_once()
                if wait and wait > 0:
                    stop.wait(wait)

        self._thread = threading.Thread(
            target=loop, name=f"singleton-{self.name}", daemon=True
        )
        self._thread.start()
        return self._thread


class Typed:
    """Key-based decorator around an object controller (typed.go:50-81).

    Reconciling by key instead of by object means the inner controller
    always receives a FRESH fetch — never a stale watch/list copy — a
    NotFound key is silently ignored (typed.go:73-75), and an object
    mid-deletion is routed to the inner controller's finalize() when it
    implements one (FinalizingTypedController, typed.go:39-43,76-78)."""

    def __init__(self, kube_client, kind: str, inner):
        self.kube_client = kube_client
        self.kind = kind
        self.inner = inner
        self.name = f"{kind.lower()}.{type(inner).__name__}"

    def reconcile_key(self, name: str, namespace: str = ""):
        obj = self.kube_client.get(self.kind, namespace, name)
        if obj is None:
            return None
        if obj.metadata.deletion_timestamp is not None and hasattr(
            self.inner, "finalize"
        ):
            return self.inner.finalize(obj)
        return self.inner.reconcile(obj)


class _DaemonPool:
    """Minimal worker pool with DAEMON threads (unlike ThreadPoolExecutor,
    whose non-daemon workers are joined at interpreter exit — one reconcile
    wedged on a blackholed cloud API would then block process shutdown
    until SIGKILL). A wedged task here leaks its worker; the process still
    exits."""

    def __init__(self, name: str, max_workers: int):
        self._q: "queue.Queue" = queue.Queue()
        self._threads = [
            threading.Thread(
                target=self._worker, daemon=True, name=f"{name}-{i}"
            )
            for i in range(max_workers)
        ]
        for t in self._threads:
            t.start()

    def _worker(self):
        while True:
            fn, args, box, done = self._q.get()
            try:
                box["result"] = fn(*args)
            except BaseException as e:  # noqa: BLE001 — surfaced via result()
                box["error"] = e
            finally:
                done.set()

    def submit(self, fn, *args):
        box: dict = {}
        done = threading.Event()
        self._q.put((fn, args, box, done))

        def result(timeout=None):
            if not done.wait(timeout):
                raise TimeoutError("reconcile still running")
            if "error" in box:
                raise box["error"]
            return box.get("result")

        return result


# persistent per-controller worker pools: the housekeeping singleton runs
# every second — building/tearing a 50-thread pool per tick would be pure
# churn. Pools live for the process (idle daemon workers are cheap).
_pools: dict = {}
_pools_mu = threading.Lock()


def _pool(name: str, max_workers: int) -> _DaemonPool:
    key = (name, max_workers)
    with _pools_mu:
        pool = _pools.get(key)
        if pool is None:
            pool = _pools[key] = _DaemonPool(name, max_workers)
        return pool


def reconcile_concurrently(name: str, items, reconcile_fn, max_workers: int = 10):
    """Bounded parallel reconciles over a batch of objects — the
    MaxConcurrentReconciles analog (the reference runs 50 parallel machine
    reconciles, machine/controller.go:166, and 10 for provisioning,
    provisioning/controller.go:72). Errors are counted/logged per
    controller and never abort the batch; returns the error count."""
    items = list(items)
    if not items:
        return 0

    def one(obj):
        with with_controller_name(name), log_bound(controller=name):
            return reconcile_fn(obj)

    errors = 0
    results = [_pool(name, max_workers).submit(one, obj) for obj in items]
    for result in results:
        try:
            result()
        except Exception:
            RECONCILE_ERRORS.inc(labels={"controller": name})
            LOG.exception("reconcile failed", controller=name)
            errors += 1
    return errors
