"""Operator runtime: wires informers + controllers over the in-memory kube
store and runs them.

Mirrors reference pkg/operator + pkg/controllers/controllers.go:46-73 (the
one place all 13 controllers are wired) and operator/controller/singleton.go
(self-clocked loops). The reference's manager/watch machinery maps to watch
pump threads; leader election is a no-op single-process lease; the TPU solver
replaces Scheduler.Solve behind the Solver interface.

Two run modes:
  step()  — synchronous single pass over every controller (deterministic for
            tests and simulations; the envtest-style harness)
  start() — background threads: watch pumps + singleton loops
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_core_tpu.api.settings import Settings, set_current
from karpenter_core_tpu.controllers.counter.controller import CounterController
from karpenter_core_tpu.controllers.inflightchecks.controller import InflightChecksController
from karpenter_core_tpu.controllers.machine.controller import MachineController
from karpenter_core_tpu.controllers.machine.terminator import EvictionQueue, Terminator
from karpenter_core_tpu.controllers.metrics.controllers import (
    NodeMetricsController,
    PodMetricsController,
    ProvisionerMetricsController,
)
from karpenter_core_tpu.controllers.node.controller import NodeController
from karpenter_core_tpu.controllers.provisioning.provisioner import (
    PodController,
    ProvisioningController,
)
from karpenter_core_tpu.controllers.termination.controller import TerminationController
from karpenter_core_tpu.events import Recorder
from karpenter_core_tpu.kube.client import InMemoryKubeClient
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.state.informer import (
    MachineInformer,
    NodeInformer,
    PodInformer,
    ProvisionerInformer,
)


@dataclass
class Operator:
    """The assembled control plane (controllers.go:46-73)."""

    kube_client: InMemoryKubeClient
    cloud_provider: object
    cluster: Cluster
    recorder: Recorder
    provisioning: ProvisioningController
    pod_controller: PodController
    machine_controller: MachineController
    node_controller: NodeController
    termination_controller: TerminationController
    inflight_checks: InflightChecksController
    counter: CounterController
    deprovisioning: object
    node_metrics: NodeMetricsController
    pod_metrics: PodMetricsController
    provisioner_metrics: ProvisionerMetricsController
    eviction_queue: EvictionQueue
    terminator: Terminator
    clock: object = time.time
    # deflake hook: zero-arg callable injecting randomized delays into the
    # watch pumps (reference pkg/test/randomdelay.go:44-70); None in prod
    jitter: object = None
    # watch staleness bound: a pump that has seen NO event for this many
    # seconds relists (informer resync analog) so a silently-dead stream
    # converges; 0 disables the periodic resync (fault-driven relists
    # still fire). The default is deliberately long — a relist is a full
    # LIST + redelivery per kind (50k objects at the design target), and
    # the apiserver client's pump already reconnects/relists internally on
    # stream drops, so this is a last-resort liveness net, not the primary
    # recovery path (real informers resync on hours-scale defaults).
    watch_relist_interval: float = 600.0
    _threads: List[threading.Thread] = field(default_factory=list)
    _stop: threading.Event = field(default_factory=threading.Event)

    # -- synchronous harness (envtest analog) ------------------------------

    def sync_state(self) -> None:
        """Pump current store contents through the informers, including
        deletions (a level-triggered relist: objects the cluster tracks that
        are gone from the store get a synthetic DELETED event — the sync
        analog of the watch pumps)."""
        from karpenter_core_tpu.kube.objects import object_key

        node_inf = NodeInformer(self.cluster)
        pod_inf = PodInformer(self.cluster)
        machine_inf = MachineInformer(self.cluster)
        nodes = self.kube_client.list("Node")
        machines = self.kube_client.list("Machine")
        pods = self.kube_client.list("Pod")
        live_nodes = {n.metadata.name for n in nodes}
        live_machines = {m.metadata.name for m in machines}
        for state_node in self.cluster.nodes():
            # node and machine records expire independently: a Machine can be
            # deleted while its Node lives on (and vice versa)
            if (
                state_node.machine is not None
                and state_node.machine.metadata.name not in live_machines
            ):
                self.cluster.delete_machine(state_node.machine.metadata.name)
            if state_node.node is not None and state_node.node.metadata.name not in live_nodes:
                self.cluster.delete_node(state_node.node.metadata.name)
        live_pods = {object_key(p) for p in pods}
        for key in list(self.cluster.bindings):
            if key not in live_pods:
                self.cluster.delete_pod(key)
        for node in nodes:
            node_inf.handle("MODIFIED", node)
        for machine in machines:
            machine_inf.handle("MODIFIED", machine)
        for pod in pods:
            pod_inf.handle("MODIFIED", pod)

    def step(self, provision: bool = True, deprovision: bool = False) -> dict:
        """One synchronous pass over the controller chain. Returns a summary
        of actions taken."""
        self.sync_state()
        summary = {"launched": 0, "deprovisioned": False}
        for machine in self.kube_client.list("Machine"):
            self.machine_controller.reconcile(machine)
        for node in self.kube_client.list("Node"):
            self.node_controller.reconcile(node)
            self.termination_controller.reconcile(node)
        self.sync_state()
        if provision:
            summary["launched"] = self.provisioning.reconcile(wait_timeout=None)
            self.sync_state()
        for machine in self.kube_client.list("Machine"):
            self.machine_controller.reconcile(machine)
        provisioners = self.kube_client.list("Provisioner")
        for provisioner in provisioners:
            self.counter.reconcile(provisioner)
            self.provisioner_metrics.reconcile(provisioner)
        self.provisioner_metrics.prune({p.name for p in provisioners})
        if deprovision and self.deprovisioning is not None:
            summary["deprovisioned"] = self.deprovisioning.reconcile()
        self.node_metrics.reconcile()
        self.eviction_queue.drain()
        return summary

    # -- background runtime -------------------------------------------------

    def start(self) -> None:
        """Watch pumps + singleton loops (operator.go:154-169)."""
        self.eviction_queue.start()
        watches = [
            ("Node", NodeInformer(self.cluster).handle),
            ("Pod", PodInformer(self.cluster).handle),
            ("Machine", MachineInformer(self.cluster).handle),
            ("Provisioner", ProvisionerInformer(self.cluster).handle),
        ]
        import queue as queue_mod

        from karpenter_core_tpu import chaos
        from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
        from karpenter_core_tpu.obs.log import get_logger
        from karpenter_core_tpu.operator.controller import RECONCILE_ERRORS

        relists = REGISTRY.counter(
            f"{NAMESPACE}_watch_relists_total",
            "Watch relists after a dropped/stale stream or failed event "
            "delivery, by kind (the informer list-then-watch recovery)",
        )
        log = get_logger("karpenter.operator")
        for kind, handler in watches:
            q = self.kube_client.watch(kind)

            def deliver(event, obj, handler=handler, kind=kind):
                """One event through the informer + per-kind side effects —
                shared by the live stream and the relist replay so recovery
                re-drives the SAME reactions (pod batching, metric prune)."""
                chaos.maybe_fail(chaos.STATE_WATCH)
                handler(event, obj)
                if kind == "Pod":
                    if event != "DELETED":
                        self.pod_controller.reconcile(obj)
                    self.pod_metrics.reconcile(obj, deleted=event == "DELETED")
                elif kind == "Provisioner":
                    self.provisioner_metrics.reconcile(
                        obj, deleted=event == "DELETED"
                    )

            def relist(known, deliver=deliver, kind=kind):
                """Backlog relist after a gap (failed delivery, staleness
                timeout): replay the store as MODIFIED and synthesize
                DELETED for objects that vanished while deliveries were
                failing, so the cluster state holds no ghosts. The existing
                queue is KEPT — both client implementations keep their
                subscriptions valid across gaps (the in-memory queue cannot
                break; the apiserver pump reconnects-and-relists
                internally), so resubscribing here would only leak pumps
                and double-list. Replays may duplicate live events —
                level-triggered consumers tolerate that."""
                relists.inc({"kind": kind})
                current = {}
                for obj in self.kube_client.list(kind):
                    key = (getattr(obj.metadata, "namespace", ""),
                           obj.metadata.name)
                    current[key] = True
                    deliver("MODIFIED", obj)
                for key in list(known):
                    if key not in current:
                        gone = self.kube_client.new_object(kind)
                        gone.metadata.namespace, gone.metadata.name = key
                        deliver("DELETED", gone)
                known.clear()
                known.update(current)

            def pump(q=q, deliver=deliver, relist=relist, kind=kind):
                known: dict = {}
                last_event = time.monotonic()
                while not self._stop.is_set():
                    try:
                        try:
                            event, obj = q.get(timeout=0.1)
                        except queue_mod.Empty:
                            # staleness: a stream that has gone silent past
                            # the resync bound relists — a dead pump and a
                            # quiet cluster look identical from here, and a
                            # relist is cheap + idempotent for level-
                            # triggered consumers
                            if (
                                self.watch_relist_interval
                                and time.monotonic() - last_event
                                >= self.watch_relist_interval
                            ):
                                relist(known)
                                last_event = time.monotonic()
                            continue
                        last_event = time.monotonic()
                        # deflake hook: the test harness injects randomized
                        # delays here to shake out pump/singleton races
                        # (reference randomdelay.go:44-70, make deflake)
                        jitter = self.jitter
                        if jitter is not None:
                            jitter()
                        deliver(event, obj)
                        # track known keys only AFTER a successful delivery:
                        # a failed DELETED delivery must keep its key so the
                        # recovery relist still diffs it into a synthetic
                        # DELETED instead of leaving a ghost
                        key = (getattr(obj.metadata, "namespace", ""),
                               obj.metadata.name)
                        if event == "DELETED":
                            known.pop(key, None)
                        else:
                            known[key] = True
                    except Exception:
                        RECONCILE_ERRORS.inc(labels={"controller": f"watch-{kind}"})
                        log.exception("watch pump failed", kind=kind)
                        # the failed event is lost from the stream's point
                        # of view: recover by relisting so the store state
                        # (including whatever that event carried) lands —
                        # retried until it sticks (degrade, never stall; a
                        # watch_relist_interval of 0 must still converge)
                        while not self._stop.is_set():
                            try:
                                relist(known)
                                last_event = time.monotonic()
                                break
                            except Exception:
                                log.exception("watch relist failed", kind=kind)
                                self._stop.wait(0.2)

            t = threading.Thread(
                target=pump, daemon=True, name=f"operator-watch-{kind}"
            )
            t.start()
            self._threads.append(t)

        from karpenter_core_tpu.operator.controller import Singleton

        def provision_once():
            self.provisioning.reconcile(wait_timeout=0.2)
            return 0.0  # the batcher is the rate limiter

        def deprovision_once():
            if self.deprovisioning is not None:
                self.deprovisioning.reconcile()
            return None

        def housekeeping_once():
            from karpenter_core_tpu.operator.controller import (
                Typed,
                reconcile_concurrently,
            )

            # key-based typed reconcilers (typed.go:50-81): each worker
            # re-fetches its object so list-to-reconcile races see fresh
            # state, and deleting objects route to finalize()
            typed_machine = Typed(self.kube_client, "Machine", self.machine_controller)
            typed_node = Typed(self.kube_client, "Node", self.node_controller)
            typed_termination = Typed(
                self.kube_client, "Node", self.termination_controller
            )

            # MaxConcurrentReconciles analog: machine reconciles fan out 50
            # wide, node 10 wide (machine/controller.go:166,
            # provisioning/controller.go:72); cloud/API-bound work overlaps
            reconcile_concurrently(
                "machine", self.kube_client.list("Machine"),
                lambda m: typed_machine.reconcile_key(m.metadata.name),
                max_workers=50,
            )

            def node_reconcile(node):
                typed_node.reconcile_key(node.metadata.name)
                typed_termination.reconcile_key(node.metadata.name)

            reconcile_concurrently(
                "node", self.kube_client.list("Node"), node_reconcile,
                max_workers=10,
            )
            for provisioner in self.kube_client.list("Provisioner"):
                self.counter.reconcile(provisioner)
            self.node_metrics.reconcile()
            return None

        # rate-limited singleton loops with duration/error instrumentation
        # (singleton.go:58-129) — a crashing reconcile is logged, counted,
        # and backed off, never silently swallowed
        self.singletons = [
            Singleton("provisioning", provision_once, interval=0.0),
            Singleton("deprovisioning", deprovision_once, interval=1.0),
            Singleton("housekeeping", housekeeping_once, interval=1.0),
        ]
        for singleton in self.singletons:
            self._threads.append(singleton.start(self._stop))

    def stop(self) -> None:
        self._stop.set()
        self.eviction_queue.stop()
        # join the pumps/singletons so no stale thread mutates state (or
        # trips error counters) after stop() returns — bounded wait, the
        # threads are daemons either way
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()


def new_operator(
    cloud_provider,
    kube_client: Optional[InMemoryKubeClient] = None,
    settings: Optional[Settings] = None,
    solver=None,
    clock=None,
    with_webhooks: bool = False,
) -> Operator:
    """Assemble the full control plane (controllers.go:46-73).

    with_webhooks installs admission defaulting/validation on the client
    (operator.WithWebhooks, operator.go:149-152); off by default because
    test suites create intentionally-partial objects."""
    # clock resolves at CALL time (the monotonic-time-default lint rule):
    # a module-level `clock=time.time` default binds at import and a
    # later-installed fake clock would silently never reach the controllers
    if clock is None:
        clock = time.time
    if settings is not None:
        set_current(settings)
    from karpenter_core_tpu.cloudprovider.metrics import decorate

    # per-controller SPI duration attribution (cloudprovider/metrics decorator)
    cp_provisioning = decorate(cloud_provider, "provisioning")
    cp_machine = decorate(cloud_provider, "machine")
    cp_node = decorate(cloud_provider, "node")
    cp_deprovisioning = decorate(cloud_provider, "deprovisioning")
    cp_inflight = decorate(cloud_provider, "inflightchecks")
    kube_client = kube_client or InMemoryKubeClient()
    if with_webhooks:
        from karpenter_core_tpu.webhooks import install as install_webhooks

        install_webhooks(kube_client)
    # events post to the cluster through the client (kubectl-describe
    # visibility) on top of the in-memory ring (recorder.go:50-56)
    recorder = Recorder(clock=clock, kube_client=kube_client)
    cluster = Cluster(kube_client, cp_node, clock=clock)
    eviction_queue = EvictionQueue(kube_client, recorder)
    terminator = Terminator(kube_client, cp_machine, eviction_queue, clock=clock)
    provisioning = ProvisioningController(
        kube_client, cp_provisioning, cluster, recorder=recorder, solver=solver,
        clock=clock,
    )
    from karpenter_core_tpu.controllers.deprovisioning.controller import (
        DeprovisioningController,
    )

    deprovisioning = DeprovisioningController(
        kube_client, cluster, provisioning, cp_deprovisioning, recorder, clock=clock
    )
    return Operator(
        kube_client=kube_client,
        cloud_provider=cloud_provider,
        cluster=cluster,
        recorder=recorder,
        provisioning=provisioning,
        pod_controller=PodController(provisioning),
        machine_controller=MachineController(
            kube_client, cp_machine, cluster, terminator, recorder, clock=clock
        ),
        node_controller=NodeController(kube_client, cp_node, cluster, clock=clock),
        termination_controller=TerminationController(
            kube_client, terminator, cluster, recorder
        ),
        inflight_checks=InflightChecksController(
            kube_client, cp_inflight, cluster, recorder, clock=clock
        ),
        counter=CounterController(kube_client, cluster),
        deprovisioning=deprovisioning,
        node_metrics=NodeMetricsController(cluster),
        pod_metrics=PodMetricsController(kube_client, clock=clock),
        provisioner_metrics=ProvisionerMetricsController(kube_client),
        eviction_queue=eviction_queue,
        terminator=terminator,
        clock=clock,
    )
