"""Context-carried configuration (reference pkg/operator/injection:36-127).

The reference threads Options, Settings, and the controller name through
context.Context so any depth of the call stack can read them without
plumbing. contextvars are the Python analog: Singleton.reconcile_once sets
the controller name around each reconcile, the operator entrypoint sets
options/settings at startup, and log lines / metrics helpers read them
without signature changes.
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import Iterator, Optional

_options: contextvars.ContextVar = contextvars.ContextVar("karpenter_options",
                                                          default=None)
_settings: contextvars.ContextVar = contextvars.ContextVar("karpenter_settings",
                                                           default=None)
_controller: contextvars.ContextVar = contextvars.ContextVar(
    "karpenter_controller", default=""
)
# process-level bootstrap values: new THREADS do not inherit ContextVar
# values set elsewhere (each thread starts a fresh context), so the
# operator-startup defaults live in module globals and the getters fall
# back to them — context overrides still win within a scope
_default_options = None
_default_settings = None


@contextlib.contextmanager
def with_options(options) -> Iterator[None]:
    token = _options.set(options)
    try:
        yield
    finally:
        _options.reset(token)


def get_options():
    o = _options.get()
    return o if o is not None else _default_options


@contextlib.contextmanager
def with_settings(settings) -> Iterator[None]:
    token = _settings.set(settings)
    try:
        yield
    finally:
        _settings.reset(token)


def get_settings():
    """Context settings first, then the injected process defaults, then
    the process-global current settings (settings.go:53-68 falls back the
    same way)."""
    s = _settings.get()
    if s is not None:
        return s
    if _default_settings is not None:
        return _default_settings
    from karpenter_core_tpu.api.settings import current

    return current()


@contextlib.contextmanager
def with_controller_name(name: str) -> Iterator[None]:
    token = _controller.set(name)
    try:
        yield
    finally:
        _controller.reset(token)


def controller_name() -> str:
    return _controller.get()


def inject_defaults(options=None, settings=None) -> None:
    """Process-level bootstrap (injection.go:116-127): set the base values
    once at operator startup — visible from EVERY thread (module globals,
    since threads do not inherit another thread's ContextVars)."""
    global _default_options, _default_settings
    if options is not None:
        _default_options = options
    if settings is not None:
        _default_settings = settings
