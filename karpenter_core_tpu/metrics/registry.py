"""Minimal in-process metrics registry (counters, gauges, histograms).

The reference exposes prometheus metrics (pkg/metrics/metrics.go:13-38 and
per-controller instruments). This registry mirrors that surface — namespaced
metric names, label sets, duration buckets — with an in-memory store and a
text exposition dump, so the operator runtime can serve/inspect the same
signals without a prometheus client dependency. expose() emits the real
Prometheus text format (HELP/TYPE lines, cumulative histogram buckets with
the +Inf series, escaped label values) so promtool and a real scraper can
parse the endpoint.
"""
from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter"

# metrics.go DurationBuckets
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300,
]


LabelValues = Tuple[Tuple[str, str], ...]


def _labels(labels: Optional[Dict[str, str]]) -> LabelValues:
    return tuple(sorted((labels or {}).items()))


def _escape_label(value: str) -> str:
    """Label-value escaping per the exposition format: backslash, quote,
    newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(lv: LabelValues, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in lv]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(value: float) -> str:
    """Full-precision sample rendering (the python client's convention):
    %g's 6 significant digits would corrupt large counters/sums under
    rate()/increase() on a real scraper."""
    if value == int(value) and abs(value) < 1e17:
        return str(int(value))
    return repr(float(value))


def _fmt_exemplar(ex: Optional[Tuple[Dict[str, str], float]]) -> str:
    """OpenMetrics exemplar suffix for a bucket line ('' when absent):
    ` # {trace_id="t42"} 0.93` — the metric -> trace -> flight-record link
    (ISSUE 15). Rendered only on lines that carry one, so exemplar-free
    exposition stays byte-identical to the plain 0.0.4 format."""
    if not ex:
        return ""
    labels, value = ex
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return f" # {{{inner}}} {_fmt_value(value)}"


def _render_external(name: str, kind: str, fam: dict) -> List[str]:
    """Sample lines for one external family (no HELP/TYPE — the caller
    emitted the one header for this name). Series labels are rendered
    as-is; the source is responsible for disambiguating its series from
    the local ones (the solver host adds a `process` label)."""
    lines: List[str] = []
    series = sorted(
        ((_labels(labels), value) for labels, value in fam.get("series", ())),
    )
    if kind in ("counter", "gauge"):
        for lv, value in series:
            try:
                lines.append(f"{name}{_fmt_labels(lv)} {_fmt_value(value)}")
            except (TypeError, ValueError):
                continue
        return lines
    bounds = list(fam.get("buckets", ()))
    for lv, hist in series:
        if not isinstance(hist, dict):
            continue
        counts = list(hist.get("buckets", ()))
        count = int(hist.get("count", 0))
        for bound, c in zip(bounds, counts):
            le = _fmt_labels(lv, f'le="{bound:g}"')
            lines.append(f"{name}_bucket{le} {int(c)}")
        inf = _fmt_labels(lv, 'le="+Inf"')
        lines.append(f"{name}_bucket{inf} {count}")
        lines.append(
            f"{name}_sum{_fmt_labels(lv)} {_fmt_value(float(hist.get('sum', 0.0)))}"
        )
        lines.append(f"{name}_count{_fmt_labels(lv)} {count}")
    return lines


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self.values: Dict[LabelValues, float] = defaultdict(float)

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0) -> None:
        with self._mu:
            self.values[_labels(labels)] += value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._mu:
            return self.values.get(_labels(labels), 0.0)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        """Snapshot of every labeled series: [(labels, value), ...]. Used
        by out-of-process reporters (the solver host's stats frame) that
        need the whole counter, not one label combination."""
        with self._mu:
            return [(dict(lv), v) for lv, v in self.values.items()]


class Gauge:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self.values: Dict[LabelValues, float] = {}

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self.values[_labels(labels)] = value

    def get(self, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        with self._mu:
            return self.values.get(_labels(labels))

    def delete(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self.values.pop(_labels(labels), None)

    def clear(self) -> None:
        with self._mu:
            self.values.clear()

    def replace_all(self, pairs: Iterable[Tuple[float, Optional[Dict[str, str]]]]) -> None:
        """Atomically swap the whole series set to `pairs` ((value, labels)
        tuples): scrapers reading under the same lock (expose) see either
        the previous generation or the new one, never a cleared/partial
        one — the clear()-then-set scrape race's fix."""
        new_values = {_labels(labels): value for value, labels in pairs}
        with self._mu:
            self.values = new_values


class Histogram:
    def __init__(self, name: str, help: str = "", buckets: Iterable[float] = DURATION_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets)
        self._mu = threading.Lock()
        # bucket_counts are CUMULATIVE (le semantics), matching exposition
        self.bucket_counts: Dict[LabelValues, List[int]] = {}
        self.sums: Dict[LabelValues, float] = defaultdict(float)
        self.counts: Dict[LabelValues, int] = defaultdict(int)
        # last exemplar per (series, bucket index): {trace_id: ...} labels +
        # the observed value, rendered OpenMetrics-style on bucket lines so
        # a bad p99 bucket links metric -> trace -> flight record (ISSUE 15)
        self.exemplars: Dict[LabelValues, Dict[int, Tuple[Dict[str, str], float]]] = {}

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        lv = _labels(labels)
        with self._mu:
            counts = self.bucket_counts.setdefault(lv, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            for b in range(i, len(self.buckets)):
                counts[b] += 1
            self.sums[lv] += value
            self.counts[lv] += 1
            if exemplar:
                # i == len(buckets) attaches to the +Inf bucket
                self.exemplars.setdefault(lv, {})[i] = (dict(exemplar), value)

    def series(self) -> List[Tuple[Dict[str, str], Dict[str, object]]]:
        """Snapshot of every labeled series: [(labels, {"buckets":
        cumulative-counts, "sum": s, "count": n}), ...] — the histogram
        twin of Counter.series(), ridden by out-of-process reporters (the
        solver host's stats frame, ISSUE 15)."""
        with self._mu:
            return [
                (
                    dict(lv),
                    {
                        "buckets": list(self.bucket_counts.get(lv, ())),
                        "sum": self.sums[lv],
                        "count": count,
                    },
                )
                for lv, count in self.counts.items()
            ]

    def snapshot(self, labels: Optional[Dict[str, str]] = None):
        """(cumulative bucket counts, count, sum) at this instant — pass a
        snapshot back into percentile()/count_since() as `baseline` to read
        the distribution of ONLY the observations made since (counters are
        process-cumulative; SLO windows like the soak bench are not)."""
        lv = _labels(labels)
        with self._mu:
            return (
                list(self.bucket_counts.get(lv, ())),
                self.counts[lv],
                self.sums[lv],
            )

    def count_since(self, baseline=None, labels: Optional[Dict[str, str]] = None) -> int:
        lv = _labels(labels)
        with self._mu:
            return self.counts[lv] - (baseline[1] if baseline else 0)

    def merged_snapshot(self):
        """Cross-series aggregate in snapshot() shape: (cumulative bucket
        counts, count, sum) summed over EVERY labeled series. The
        attribution plane splits one logical stream into per-tenant series
        (ISSUE 16); readers that want the whole stream regardless of who
        it was billed to — the soak driver's SLO math — baseline-diff
        against this instead of the unlabeled series."""
        with self._mu:
            agg = [0] * len(self.buckets)
            total = 0
            s = 0.0
            for lv, count in list(self.counts.items()):
                for i, v in enumerate(self.bucket_counts.get(lv, ())):
                    agg[i] += v
                total += count
                s += self.sums[lv]
            return agg, total, s

    def merged_percentile(self, q: float, baseline=None) -> Optional[float]:
        """percentile() over the merged_snapshot() aggregate; `baseline`
        must also be a merged_snapshot()."""
        counts, total, _ = self.merged_snapshot()
        base_counts, base_total = (
            (baseline[0], baseline[1]) if baseline else ((), 0)
        )
        total -= base_total
        if total <= 0:
            return None
        target = q * total
        for i, (bucket, c) in enumerate(zip(self.buckets, counts)):
            c -= base_counts[i] if i < len(base_counts) else 0
            if c >= target:
                return bucket
        return self.buckets[-1]

    def percentile(self, q: float, labels: Optional[Dict[str, str]] = None,
                   baseline=None) -> Optional[float]:
        """Upper bucket bound at quantile q; values above the largest
        finite bucket saturate to it (histogram_quantile's convention).
        With `baseline` (a prior snapshot()), quantiles cover only the
        observations recorded after the snapshot."""
        lv = _labels(labels)
        base_counts, base_total = (
            (baseline[0], baseline[1]) if baseline else ((), 0)
        )
        with self._mu:
            counts = self.bucket_counts.get(lv)
            total = self.counts[lv] - base_total
            if not counts or total <= 0:
                return None
            target = q * total
            for i, (bucket, c) in enumerate(zip(self.buckets, counts)):
                c -= base_counts[i] if i < len(base_counts) else 0
                if c >= target:
                    return bucket
            return self.buckets[-1]


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self.metrics: Dict[str, object] = {}
        # external sample sources (ISSUE 15): objects with a `families()`
        # method returning {name: {"kind", "help", "buckets", "series"}} —
        # the solver host's merged child-process metrics register here so
        # the ONE exposition carries both processes' series (same metric
        # family, disjoint label sets: child series carry a `process`
        # label). Registered sources must never raise from families().
        self._externals: List[object] = []

    def add_external(self, source) -> None:
        with self._mu:
            if source not in self._externals:
                self._externals.append(source)

    def remove_external(self, source) -> None:
        with self._mu:
            if source in self._externals:
                self._externals.remove(source)

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", buckets=DURATION_BUCKETS) -> Histogram:
        return self._get_or_create(
            name, Histogram, lambda: Histogram(name, help, buckets)
        )

    def _get_or_create(self, name: str, cls, factory):
        with self._mu:
            existing = self.metrics.get(name)
            if existing is None:
                existing = self.metrics[name] = factory()
            elif not isinstance(existing, cls):
                raise TypeError(
                    f"metric {name!r} is already registered as "
                    f"{type(existing).__name__}, not {cls.__name__}"
                )
            return existing

    def expose(self, exemplars: bool = False) -> str:
        """Prometheus text exposition (format version 0.0.4 by default).

        ``exemplars=True`` appends OpenMetrics `# {…}` exemplar suffixes
        on histogram bucket lines that carry one — callers serving that
        form MUST declare the openmetrics content type (the 0.0.4 parser
        treats the suffix as a malformed timestamp and fails the whole
        scrape), which is why the default exposition never renders them:
        exemplars are only reachable through content negotiation
        (operator /metrics honors `Accept: application/openmetrics-text`).
        External sources' series render under the same family header as
        the local metric of that name (one HELP/TYPE per name — duplicate
        headers are illegal exposition), after the local series."""
        lines: List[str] = []
        with self._mu:
            metrics = dict(self.metrics)
            externals = list(self._externals)
        ext_families: Dict[str, List[dict]] = {}
        for source in externals:
            try:
                fams = source.families()
            except Exception:  # noqa: BLE001 — a sick source must not kill /metrics
                continue
            for name, fam in (fams or {}).items():
                ext_families.setdefault(name, []).append(fam)
        for name in sorted(set(metrics) | set(ext_families)):
            metric = metrics.get(name)
            fams = ext_families.get(name, [])
            help_text = metric.help if metric is not None else next(
                (f.get("help", "") for f in fams if f.get("help")), ""
            )
            if metric is not None:
                kind = (
                    "counter" if isinstance(metric, Counter)
                    else "gauge" if isinstance(metric, Gauge)
                    else "histogram"
                )
            else:
                kind = str(fams[0].get("kind", "counter"))
            if help_text:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {kind}")
            if isinstance(metric, (Counter, Gauge)):
                with metric._mu:
                    values = dict(metric.values)
                for lv, value in sorted(values.items()):
                    lines.append(f"{name}{_fmt_labels(lv)} {_fmt_value(value)}")
            elif isinstance(metric, Histogram):
                with metric._mu:
                    series = {
                        lv: (
                            list(metric.bucket_counts.get(lv, [])),
                            metric.sums[lv],
                            count,
                            dict(metric.exemplars.get(lv, ())),
                        )
                        for lv, count in metric.counts.items()
                    }
                for lv, (buckets, total_sum, count, ex) in sorted(
                    series.items()
                ):
                    for i, (bound, c) in enumerate(zip(metric.buckets, buckets)):
                        le = _fmt_labels(lv, f'le="{bound:g}"')
                        lines.append(
                            f"{name}_bucket{le} {c}"
                            + (_fmt_exemplar(ex.get(i)) if exemplars else "")
                        )
                    inf = _fmt_labels(lv, 'le="+Inf"')
                    lines.append(
                        f"{name}_bucket{inf} {count}"
                        + (_fmt_exemplar(ex.get(len(metric.buckets)))
                           if exemplars else "")
                    )
                    lines.append(
                        f"{name}_sum{_fmt_labels(lv)} {_fmt_value(total_sum)}"
                    )
                    lines.append(f"{name}_count{_fmt_labels(lv)} {count}")
            for fam in fams:
                lines.extend(_render_external(name, kind, fam))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# cross-process metric merging (ISSUE 15): the solver-host child snapshots
# its registry into a JSON-able families dict that rides the stats frame;
# the parent folds snapshots into a ProcessSeriesMerger registered as an
# external exposition source, so child counters/histograms appear in the
# ONE parent /metrics under a `process` label — double-count-proof across
# kill->respawn cycles (cumulative snapshots are keyed by child generation;
# a dead generation's last snapshot is committed once, never re-added).


def snapshot_families(registry: "Registry", prefix: str = NAMESPACE + "_",
                      max_series: int = 512) -> Dict[str, dict]:
    """JSON-able cumulative snapshot of a registry's counters + histograms
    (gauges deliberately excluded: a dead child's last gauge reading is
    not a fact about the parent process). Bounded: at most `max_series`
    series total — oversized registries truncate deterministically (sorted
    name order) rather than bloat the frame."""
    out: Dict[str, dict] = {}
    with registry._mu:
        metrics = dict(registry.metrics)
    budget = max_series
    for name in sorted(metrics):
        if budget <= 0:
            break
        if not name.startswith(prefix):
            continue
        metric = metrics[name]
        if isinstance(metric, Counter):
            series = [
                [labels, value] for labels, value in metric.series()
            ][:budget]
            if not series:
                continue
            out[name] = {"kind": "counter", "help": metric.help,
                         "series": series}
        elif isinstance(metric, Histogram):
            series = [
                [labels, state] for labels, state in metric.series()
            ][:budget]
            if not series:
                continue
            out[name] = {
                "kind": "histogram", "help": metric.help,
                "buckets": list(metric.buckets), "series": series,
            }
        else:
            continue
        budget -= len(out[name]["series"])
    return out


def _merge_state(kind: str, a, b):
    """a + b for one series' cumulative state (scalar or histogram dict)."""
    if kind != "histogram":
        return float(a) + float(b)
    ab, bb = list(a.get("buckets", ())), list(b.get("buckets", ()))
    if len(ab) < len(bb):
        ab += [0] * (len(bb) - len(ab))
    elif len(bb) < len(ab):
        bb += [0] * (len(ab) - len(bb))
    return {
        "buckets": [int(x) + int(y) for x, y in zip(ab, bb)],
        "sum": float(a.get("sum", 0.0)) + float(b.get("sum", 0.0)),
        "count": int(a.get("count", 0)) + int(b.get("count", 0)),
    }


class ProcessSeriesMerger:
    """Merged view over one child process's cumulative metric snapshots.

    Contract (the respawn-idempotency story, asserted in
    tests/test_solver_host.py):

      * ``ingest(generation, families)`` REPLACES the live view for that
        generation — re-ingesting the same cumulative snapshot is a no-op
        on the merged totals (snapshots are states, not deltas);
      * a generation bump (respawn) folds the previous generation's last
        snapshot into the committed base exactly once, so a child that
        died counting 7 solves contributes 7 forever, and its successor
        counts from 0 on top;
      * ``retire(generation)`` folds eagerly on a kill, so the exposition
        never loses the dead child's tail while the respawn boots.

    ``families()`` renders base+live with the ``process`` label added to
    every series — the disambiguator against the parent's own series."""

    def __init__(self, process: str):
        self.process = process
        self._mu = threading.Lock()
        self._meta: Dict[str, Tuple[str, str, Tuple[float, ...]]] = {}
        # name -> {label-tuple: state}; states are scalars (counter) or
        # {"buckets","sum","count"} dicts (histogram)
        self._base: Dict[str, Dict[LabelValues, object]] = {}
        self._live: Dict[str, Dict[LabelValues, object]] = {}
        self._live_gen: Optional[int] = None

    def _parse(self, families: Dict[str, dict]) -> Dict[str, Dict[LabelValues, object]]:
        parsed: Dict[str, Dict[LabelValues, object]] = {}
        for name, fam in (families or {}).items():
            kind = str(fam.get("kind", "counter"))
            if kind not in ("counter", "histogram"):
                continue
            self._meta[name] = (
                kind, str(fam.get("help", "")),
                tuple(fam.get("buckets", ())),
            )
            parsed[name] = {
                _labels(dict(labels)): state
                for labels, state in fam.get("series", ())
            }
        return parsed

    def _fold_live_locked(self) -> None:
        for name, series in self._live.items():
            kind = self._meta.get(name, ("counter",))[0]
            base = self._base.setdefault(name, {})
            for lv, state in series.items():
                if lv in base:
                    base[lv] = _merge_state(kind, base[lv], state)
                else:
                    base[lv] = state
        self._live = {}
        self._live_gen = None

    def ingest(self, generation: int, families: Dict[str, dict]) -> None:
        with self._mu:
            if self._live_gen is not None and generation != self._live_gen:
                self._fold_live_locked()
            self._live_gen = generation
            self._live = self._parse(families)

    def retire(self, generation: int) -> None:
        """The child of `generation` is dead: commit its last snapshot to
        the base (idempotent — retiring an already-folded or never-seen
        generation is a no-op)."""
        with self._mu:
            if self._live_gen == generation:
                self._fold_live_locked()

    def clear(self) -> None:
        with self._mu:
            self._base = {}
            self._live = {}
            self._live_gen = None

    def families(self) -> Dict[str, dict]:
        with self._mu:
            names = set(self._base) | set(self._live)
            out: Dict[str, dict] = {}
            for name in sorted(names):
                kind, help_text, buckets = self._meta.get(
                    name, ("counter", "", ())
                )
                merged: Dict[LabelValues, object] = dict(
                    self._base.get(name, ())
                )
                for lv, state in self._live.get(name, {}).items():
                    if lv in merged:
                        merged[lv] = _merge_state(kind, merged[lv], state)
                    else:
                        merged[lv] = state
                series = []
                for lv in sorted(merged):
                    labels = dict(lv)
                    labels["process"] = self.process
                    series.append([labels, merged[lv]])
                fam: Dict[str, object] = {
                    "kind": kind, "help": help_text, "series": series,
                }
                if kind == "histogram":
                    fam["buckets"] = list(buckets)
                out[name] = fam
            return out


REGISTRY = Registry()

# shared instruments (pkg/metrics/metrics.go:13-38)
NODES_CREATED = REGISTRY.counter(
    f"{NAMESPACE}_nodes_created", "Nodes created in total by the framework, by reason"
)
NODES_TERMINATED = REGISTRY.counter(
    f"{NAMESPACE}_nodes_terminated", "Nodes terminated in total by the framework, by reason"
)
MACHINES_CREATED = REGISTRY.counter(f"{NAMESPACE}_machines_created")
MACHINES_TERMINATED = REGISTRY.counter(f"{NAMESPACE}_machines_terminated")
