"""Minimal in-process metrics registry (counters, gauges, histograms).

The reference exposes prometheus metrics (pkg/metrics/metrics.go:13-38 and
per-controller instruments). This registry mirrors that surface — namespaced
metric names, label sets, duration buckets — with an in-memory store and a
text exposition dump, so the operator runtime can serve/inspect the same
signals without a prometheus client dependency.
"""
from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter"

# metrics.go DurationBuckets
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300,
]


LabelValues = Tuple[Tuple[str, str], ...]


def _labels(labels: Optional[Dict[str, str]]) -> LabelValues:
    return tuple(sorted((labels or {}).items()))


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self.values: Dict[LabelValues, float] = defaultdict(float)

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0) -> None:
        with self._mu:
            self.values[_labels(labels)] += value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self.values.get(_labels(labels), 0.0)


class Gauge:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self.values: Dict[LabelValues, float] = {}

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self.values[_labels(labels)] = value

    def get(self, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        return self.values.get(_labels(labels))

    def delete(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self.values.pop(_labels(labels), None)

    def clear(self) -> None:
        with self._mu:
            self.values.clear()


class Histogram:
    def __init__(self, name: str, help: str = "", buckets: Iterable[float] = DURATION_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets)
        self._mu = threading.Lock()
        self.bucket_counts: Dict[LabelValues, List[int]] = {}
        self.sums: Dict[LabelValues, float] = defaultdict(float)
        self.counts: Dict[LabelValues, int] = defaultdict(int)

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        lv = _labels(labels)
        with self._mu:
            counts = self.bucket_counts.setdefault(lv, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            for b in range(i, len(self.buckets)):
                counts[b] += 1
            self.sums[lv] += value
            self.counts[lv] += 1

    def percentile(self, q: float, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        lv = _labels(labels)
        counts = self.bucket_counts.get(lv)
        if not counts or self.counts[lv] == 0:
            return None
        target = q * self.counts[lv]
        for bucket, c in zip(self.buckets, counts):
            if c >= target:
                return bucket
        return self.buckets[-1]


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self.metrics: Dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", buckets=DURATION_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help, buckets))

    def _get_or_create(self, name: str, factory):
        with self._mu:
            if name not in self.metrics:
                self.metrics[name] = factory()
            return self.metrics[name]

    def expose(self) -> str:
        """Prometheus-style text exposition."""
        lines = []
        with self._mu:
            metrics = dict(self.metrics)
        for name, metric in sorted(metrics.items()):
            if isinstance(metric, (Counter, Gauge)):
                for lv, value in sorted(metric.values.items()):
                    label_str = ",".join(f'{k}="{v}"' for k, v in lv)
                    lines.append(f"{name}{{{label_str}}} {value:g}")
            elif isinstance(metric, Histogram):
                for lv, count in sorted(metric.counts.items()):
                    label_str = ",".join(f'{k}="{v}"' for k, v in lv)
                    lines.append(f"{name}_count{{{label_str}}} {count}")
                    lines.append(f"{name}_sum{{{label_str}}} {metric.sums[lv]:g}")
        return "\n".join(lines)


REGISTRY = Registry()

# shared instruments (pkg/metrics/metrics.go:13-38)
NODES_CREATED = REGISTRY.counter(
    f"{NAMESPACE}_nodes_created", "Nodes created in total by the framework, by reason"
)
NODES_TERMINATED = REGISTRY.counter(
    f"{NAMESPACE}_nodes_terminated", "Nodes terminated in total by the framework, by reason"
)
MACHINES_CREATED = REGISTRY.counter(f"{NAMESPACE}_machines_created")
MACHINES_TERMINATED = REGISTRY.counter(f"{NAMESPACE}_machines_terminated")
