"""Minimal in-process metrics registry (counters, gauges, histograms).

The reference exposes prometheus metrics (pkg/metrics/metrics.go:13-38 and
per-controller instruments). This registry mirrors that surface — namespaced
metric names, label sets, duration buckets — with an in-memory store and a
text exposition dump, so the operator runtime can serve/inspect the same
signals without a prometheus client dependency. expose() emits the real
Prometheus text format (HELP/TYPE lines, cumulative histogram buckets with
the +Inf series, escaped label values) so promtool and a real scraper can
parse the endpoint.
"""
from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter"

# metrics.go DurationBuckets
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300,
]


LabelValues = Tuple[Tuple[str, str], ...]


def _labels(labels: Optional[Dict[str, str]]) -> LabelValues:
    return tuple(sorted((labels or {}).items()))


def _escape_label(value: str) -> str:
    """Label-value escaping per the exposition format: backslash, quote,
    newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(lv: LabelValues, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in lv]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(value: float) -> str:
    """Full-precision sample rendering (the python client's convention):
    %g's 6 significant digits would corrupt large counters/sums under
    rate()/increase() on a real scraper."""
    if value == int(value) and abs(value) < 1e17:
        return str(int(value))
    return repr(float(value))


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self.values: Dict[LabelValues, float] = defaultdict(float)

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0) -> None:
        with self._mu:
            self.values[_labels(labels)] += value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._mu:
            return self.values.get(_labels(labels), 0.0)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        """Snapshot of every labeled series: [(labels, value), ...]. Used
        by out-of-process reporters (the solver host's stats frame) that
        need the whole counter, not one label combination."""
        with self._mu:
            return [(dict(lv), v) for lv, v in self.values.items()]


class Gauge:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self.values: Dict[LabelValues, float] = {}

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self.values[_labels(labels)] = value

    def get(self, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        with self._mu:
            return self.values.get(_labels(labels))

    def delete(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self.values.pop(_labels(labels), None)

    def clear(self) -> None:
        with self._mu:
            self.values.clear()

    def replace_all(self, pairs: Iterable[Tuple[float, Optional[Dict[str, str]]]]) -> None:
        """Atomically swap the whole series set to `pairs` ((value, labels)
        tuples): scrapers reading under the same lock (expose) see either
        the previous generation or the new one, never a cleared/partial
        one — the clear()-then-set scrape race's fix."""
        new_values = {_labels(labels): value for value, labels in pairs}
        with self._mu:
            self.values = new_values


class Histogram:
    def __init__(self, name: str, help: str = "", buckets: Iterable[float] = DURATION_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets)
        self._mu = threading.Lock()
        # bucket_counts are CUMULATIVE (le semantics), matching exposition
        self.bucket_counts: Dict[LabelValues, List[int]] = {}
        self.sums: Dict[LabelValues, float] = defaultdict(float)
        self.counts: Dict[LabelValues, int] = defaultdict(int)

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        lv = _labels(labels)
        with self._mu:
            counts = self.bucket_counts.setdefault(lv, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            for b in range(i, len(self.buckets)):
                counts[b] += 1
            self.sums[lv] += value
            self.counts[lv] += 1

    def snapshot(self, labels: Optional[Dict[str, str]] = None):
        """(cumulative bucket counts, count, sum) at this instant — pass a
        snapshot back into percentile()/count_since() as `baseline` to read
        the distribution of ONLY the observations made since (counters are
        process-cumulative; SLO windows like the soak bench are not)."""
        lv = _labels(labels)
        with self._mu:
            return (
                list(self.bucket_counts.get(lv, ())),
                self.counts[lv],
                self.sums[lv],
            )

    def count_since(self, baseline=None, labels: Optional[Dict[str, str]] = None) -> int:
        lv = _labels(labels)
        with self._mu:
            return self.counts[lv] - (baseline[1] if baseline else 0)

    def percentile(self, q: float, labels: Optional[Dict[str, str]] = None,
                   baseline=None) -> Optional[float]:
        """Upper bucket bound at quantile q; values above the largest
        finite bucket saturate to it (histogram_quantile's convention).
        With `baseline` (a prior snapshot()), quantiles cover only the
        observations recorded after the snapshot."""
        lv = _labels(labels)
        base_counts, base_total = (
            (baseline[0], baseline[1]) if baseline else ((), 0)
        )
        with self._mu:
            counts = self.bucket_counts.get(lv)
            total = self.counts[lv] - base_total
            if not counts or total <= 0:
                return None
            target = q * total
            for i, (bucket, c) in enumerate(zip(self.buckets, counts)):
                c -= base_counts[i] if i < len(base_counts) else 0
                if c >= target:
                    return bucket
            return self.buckets[-1]


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self.metrics: Dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", buckets=DURATION_BUCKETS) -> Histogram:
        return self._get_or_create(
            name, Histogram, lambda: Histogram(name, help, buckets)
        )

    def _get_or_create(self, name: str, cls, factory):
        with self._mu:
            existing = self.metrics.get(name)
            if existing is None:
                existing = self.metrics[name] = factory()
            elif not isinstance(existing, cls):
                raise TypeError(
                    f"metric {name!r} is already registered as "
                    f"{type(existing).__name__}, not {cls.__name__}"
                )
            return existing

    def expose(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        lines: List[str] = []
        with self._mu:
            metrics = dict(self.metrics)
        for name, metric in sorted(metrics.items()):
            if metric.help:
                lines.append(f"# HELP {name} {_escape_help(metric.help)}")
            if isinstance(metric, (Counter, Gauge)):
                kind = "counter" if isinstance(metric, Counter) else "gauge"
                lines.append(f"# TYPE {name} {kind}")
                with metric._mu:
                    values = dict(metric.values)
                for lv, value in sorted(values.items()):
                    lines.append(f"{name}{_fmt_labels(lv)} {_fmt_value(value)}")
            elif isinstance(metric, Histogram):
                lines.append(f"# TYPE {name} histogram")
                with metric._mu:
                    series = {
                        lv: (
                            list(metric.bucket_counts.get(lv, [])),
                            metric.sums[lv],
                            count,
                        )
                        for lv, count in metric.counts.items()
                    }
                for lv, (buckets, total_sum, count) in sorted(series.items()):
                    for bound, c in zip(metric.buckets, buckets):
                        le = _fmt_labels(lv, f'le="{bound:g}"')
                        lines.append(f"{name}_bucket{le} {c}")
                    inf = _fmt_labels(lv, 'le="+Inf"')
                    lines.append(f"{name}_bucket{inf} {count}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(lv)} {_fmt_value(total_sum)}"
                    )
                    lines.append(f"{name}_count{_fmt_labels(lv)} {count}")
        return "\n".join(lines)


REGISTRY = Registry()

# shared instruments (pkg/metrics/metrics.go:13-38)
NODES_CREATED = REGISTRY.counter(
    f"{NAMESPACE}_nodes_created", "Nodes created in total by the framework, by reason"
)
NODES_TERMINATED = REGISTRY.counter(
    f"{NAMESPACE}_nodes_terminated", "Nodes terminated in total by the framework, by reason"
)
MACHINES_CREATED = REGISTRY.counter(f"{NAMESPACE}_machines_created")
MACHINES_TERMINATED = REGISTRY.counter(f"{NAMESPACE}_machines_terminated")
