"""Provisioner — the declarative node-pool spec.

Mirrors reference pkg/apis/v1alpha5/provisioner.go:32-136 (+ limits.go,
provisioner_status.go): labels/taints/startupTaints layered with requirements,
kubelet config, empty/expired TTLs, capacity Limits, Weight, Consolidation
toggle; plus status resources/conditions and weight ordering.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.kube.objects import (
    NodeSelectorRequirement,
    ObjectMeta,
    ResourceList,
    Taint,
)
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class KubeletConfiguration:
    """Subset of upstream kubelet config the scheduler cares about
    (machine.go:46-115): max-pods/pods-per-core feed allocatable "pods";
    reserved/eviction feed overhead."""

    cluster_dns: List[str] = field(default_factory=list)
    container_runtime: Optional[str] = None
    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    system_reserved: ResourceList = field(default_factory=dict)
    kube_reserved: ResourceList = field(default_factory=dict)
    eviction_hard: Dict[str, str] = field(default_factory=dict)
    eviction_soft: Dict[str, str] = field(default_factory=dict)
    eviction_soft_grace_period: Dict[str, str] = field(default_factory=dict)
    image_gc_high_threshold_percent: Optional[int] = None
    image_gc_low_threshold_percent: Optional[int] = None


@dataclass
class ProviderRef:
    kind: str = ""
    name: str = ""
    api_version: str = ""


@dataclass
class Consolidation:
    enabled: Optional[bool] = None


@dataclass
class Limits:
    """Capacity bounds for a provisioner (limits.go)."""

    resources: ResourceList = field(default_factory=dict)

    def exceeded_by(self, used: ResourceList) -> Optional[str]:
        """Error string if `used` exceeds any limit (limits.go ExceededBy)."""
        for name, limit in self.resources.items():
            if used.get(name, 0.0) > limit:
                return (
                    f"{name} resource usage of {used.get(name, 0.0):g} exceeds limit of {limit:g}"
                )
        return None


@dataclass
class ProvisionerSpec:
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[NodeSelectorRequirement] = field(default_factory=list)
    kubelet_configuration: Optional[KubeletConfiguration] = None
    provider: Optional[dict] = None
    provider_ref: Optional[ProviderRef] = None
    ttl_seconds_after_empty: Optional[int] = None
    ttl_seconds_until_expired: Optional[int] = None
    limits: Optional[Limits] = None
    weight: Optional[int] = None
    consolidation: Optional[Consolidation] = None


from karpenter_core_tpu.kube.objects import Condition  # shared condition shape


@dataclass
class ProvisionerStatus:
    last_scale_time: Optional[float] = None
    conditions: List[Condition] = field(default_factory=list)
    resources: ResourceList = field(default_factory=dict)


@dataclass
class Provisioner:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProvisionerSpec = field(default_factory=ProvisionerSpec)
    status: ProvisionerStatus = field(default_factory=ProvisionerStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @property
    def name(self) -> str:
        return self.metadata.name

    def consolidation_enabled(self) -> bool:
        return bool(self.spec.consolidation and self.spec.consolidation.enabled)


def order_by_weight(provisioners: List[Provisioner]) -> List[Provisioner]:
    """Descending weight; missing weight is 0 (provisioner.go:132-136)."""
    return sorted(provisioners, key=lambda p: -(p.spec.weight or 0))
