"""Dynamic global settings (reference pkg/apis/settings/settings.go:32-68).

The reference resolves these from the `karpenter-global-settings` ConfigMap and
injects them into context.Context; here they form a process-wide Settings
object threaded explicitly (or via `current()` for defaults).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class GeometryTier:
    """One rung of the solver's geometry bucket ladder.

    Every batch axis the compiled solve program is shaped by pads UP to a
    tier value (solver/encode.py `ladder_pad`), so the set of programs the
    operator can ever need is enumerable from this table alone — which is
    what makes startup AOT prewarm (solver/prewarm.py) and a shipped
    persistent compile cache product features instead of best-effort
    caching. Axes:

      pods            total pods a provisioning pass may solve (the
                      batcher's pass cap clamps to the TOP rung —
                      effective_batch_max_pods — and the prewarm sizes its
                      synthetic workloads by it; the pods-derived commit-log
                      and slot-budget axes stay fine-grained pow2, bounded
                      because the pass cap bounds the batch)
      items           pod spec-equivalence classes — the packing scan's
                      sequential work axis
      instance_types  padded width of the instance-type axis (pad rows are
                      unoffered: tmpl_type_mask False, no offerings)
      existing_nodes  padded width of the existing-node slot axis (pad rows
                      are the closed sentinels encode always minted)
    """

    name: str
    pods: int
    items: int
    instance_types: int
    existing_nodes: int


# The default ladder. Values are chosen so (a) the smallest tier matches
# the historical power-of-two floors (item bucket 32, existing bucket 8)
# — tiny test geometries keep their exact shapes — and (b) XL covers the
# north-star 50k pods x 500 types x 1000 nodes in one rung. Sizes above
# the ladder fall back to power-of-two padding (an "overflow" geometry,
# counted by karpenter_bucket_overflow_total); the provisioning batcher
# never produces one because its pass cap is clamped to the top rung
# (Settings.effective_batch_max_pods).
DEFAULT_BUCKET_LADDER: Tuple[GeometryTier, ...] = (
    GeometryTier("S", pods=128, items=32, instance_types=8, existing_nodes=8),
    GeometryTier("M", pods=1024, items=128, instance_types=32, existing_nodes=32),
    GeometryTier("L", pods=8192, items=512, instance_types=128, existing_nodes=256),
    GeometryTier("XL", pods=65536, items=2048, instance_types=512,
                 existing_nodes=1024),  # north-star: 50k pods x 500 types
)


def parse_bucket_ladder(raw: str) -> Tuple[GeometryTier, ...]:
    """Parse the ConfigMap grammar
    `name:pods:items:types:existing[,name:...]` (e.g.
    "S:128:32:16:8,XL:65536:2048:512:1024"). Tiers must be strictly
    increasing on every axis; raises ValueError otherwise."""
    tiers = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 5:
            raise ValueError(
                f"bucketLadder tier {part!r}: want name:pods:items:types:existing"
            )
        name, *dims = fields
        try:
            pods, items, types, existing = (int(d) for d in dims)
        except ValueError:
            raise ValueError(f"bucketLadder tier {part!r}: non-integer axis")
        if min(pods, items, types, existing) <= 0:
            raise ValueError(f"bucketLadder tier {part!r}: axes must be positive")
        tiers.append(GeometryTier(name, pods, items, types, existing))
    if not tiers:
        raise ValueError("bucketLadder: no tiers")
    for a, b in zip(tiers, tiers[1:]):
        if not (a.pods < b.pods and a.items < b.items
                and a.instance_types < b.instance_types
                and a.existing_nodes < b.existing_nodes):
            raise ValueError(
                f"bucketLadder: tier {b.name!r} does not strictly grow every "
                f"axis over {a.name!r}"
            )
    return tuple(tiers)


@dataclass
class Settings:
    batch_max_duration: float = 10.0  # seconds (settings.go:33)
    batch_idle_duration: float = 1.0  # seconds (settings.go:34)
    # None disables the unregistered-machine reaper (settings.go:35-37,86-91:
    # an empty ConfigMap value nils the pointer)
    ttl_after_not_registered: Optional[float] = 15 * 60.0
    drift_enabled: bool = False  # feature gate (settings.go:44)
    # 0 = unbounded (the reference behavior). A positive cap bounds the pods
    # one provisioning pass solves (oldest first; the rest re-enter the next
    # window immediately): under sustained churn an unbounded pass re-batches
    # the WHOLE backlog, so any stall inflates the batch into a new pow2 item
    # bucket — a fresh solver geometry and (on first sight) an XLA compile —
    # which stalls the loop further. The cap pins steady-state passes to a
    # stable geometry, which is also what keeps the incremental delta
    # re-solve path's resident verdict tensor reusable across solves.
    batch_max_pods: int = 0
    # the solver's geometry bucket ladder (see GeometryTier): every compiled
    # program's batch axes land on a tier value, so the program set is
    # enumerable before the first pod arrives and the startup prewarm can
    # compile it ahead of traffic
    bucket_ladder: Tuple[GeometryTier, ...] = DEFAULT_BUCKET_LADDER
    # 0 = unbounded (the reference behavior). A positive value caps how
    # many victim nodes any single consolidation pass may terminate: the
    # batched subset evaluator (ISSUE 10) ranks candidate subsets by real
    # savings, and without a cap the best-savings subset on a badly
    # over-provisioned cluster is "most of it" — this bounds the blast
    # radius per pass (multi-node prefix sizes, the emptiness sweep, and
    # empty-node deletion all clip to it; the remainder re-enters the next
    # 10s reconcile pass).
    consolidation_disruption_budget: int = 0

    def effective_batch_max_pods(self) -> int:
        """The provisioning pass cap actually enforced: the configured
        batch_max_pods when set, clamped to the ladder's top rung either
        way — a pass larger than the largest tier would mint an unlisted
        (overflow) geometry and pay a compile the prewarm never covered,
        so the batcher splits it instead (the remainder re-enters the next
        window immediately, exactly like the plain batch_max_pods path)."""
        top = self.bucket_ladder[-1].pods if self.bucket_ladder else 0
        if self.batch_max_pods and top:
            return min(self.batch_max_pods, top)
        return self.batch_max_pods or top

    def steady_state_tier(self) -> Optional[GeometryTier]:
        """The tier a steady-state provisioning pass lands on — the prewarm
        thread compiles this bucket FIRST so the common case is warm before
        the rarer large rungs. With a batch_max_pods cap the steady pass is
        at most that many pods; uncapped, assume the top rung."""
        if not self.bucket_ladder:
            return None
        if self.batch_max_pods:
            for tier in self.bucket_ladder:
                if self.batch_max_pods <= tier.pods:
                    return tier
        return self.bucket_ladder[-1]

    @classmethod
    def from_config_map(cls, data: Dict[str, str]) -> "Settings":
        """Parse the settings ConfigMap data (settings.go:53-68). Raises
        ValueError on malformed durations/booleans and on values that fail
        Validate() (settings.go:69-85) — batch windows are required-positive,
        the registration TTL may be empty (disabled) but not negative."""
        s = cls()
        if "batchMaxDuration" in data:
            s.batch_max_duration = _parse_duration(data["batchMaxDuration"])
        if "batchIdleDuration" in data:
            s.batch_idle_duration = _parse_duration(data["batchIdleDuration"])
        if "ttlAfterNotRegistered" in data:
            raw = data["ttlAfterNotRegistered"]
            s.ttl_after_not_registered = None if raw == "" else _parse_duration(raw)
        if "featureGates.driftEnabled" in data:
            raw = data["featureGates.driftEnabled"].lower()
            if raw not in ("true", "false"):
                raise ValueError(f"featureGates.driftEnabled: not a boolean: {raw!r}")
            s.drift_enabled = raw == "true"
        if "batchMaxPods" in data:
            s.batch_max_pods = int(data["batchMaxPods"])
        if "bucketLadder" in data:
            s.bucket_ladder = parse_bucket_ladder(data["bucketLadder"])
        if "consolidationDisruptionBudget" in data:
            s.consolidation_disruption_budget = int(
                data["consolidationDisruptionBudget"]
            )
        if s.consolidation_disruption_budget < 0:
            raise ValueError("consolidationDisruptionBudget cannot be negative")
        if s.batch_max_pods < 0:
            raise ValueError("batchMaxPods cannot be negative")
        if s.batch_max_duration <= 0:
            raise ValueError("batchMaxDuration cannot be negative")
        if s.batch_idle_duration <= 0:
            raise ValueError("batchIdleDuration cannot be negative")
        if s.ttl_after_not_registered is not None and s.ttl_after_not_registered <= 0:
            raise ValueError("ttlAfterNotRegistered cannot be negative")
        return s


def _parse_duration(value: str) -> float:
    """Parse a Go-style duration string ("10s", "1m30s", "500ms"); rejects
    malformed input like Go's time.ParseDuration."""
    import re

    value = value.strip()
    unit_re = r"[0-9]+(?:\.[0-9]*)?(?:h|m(?!s)|s|ms|us|ns)"
    if not re.fullmatch(f"(?:{unit_re})+", value):
        raise ValueError(f"cannot parse duration {value!r}")
    matches = re.findall(r"([0-9]+(?:\.[0-9]*)?)(h|m(?!s)|s|ms|us|ns)", value)
    unit_seconds = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}
    return sum(float(n) * unit_seconds[u] for n, u in matches)


_current = Settings()


def current() -> Settings:
    return _current


def set_current(settings: Settings) -> None:
    global _current
    _current = settings
