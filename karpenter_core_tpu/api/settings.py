"""Dynamic global settings (reference pkg/apis/settings/settings.go:32-68).

The reference resolves these from the `karpenter-global-settings` ConfigMap and
injects them into context.Context; here they form a process-wide Settings
object threaded explicitly (or via `current()` for defaults).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class Settings:
    batch_max_duration: float = 10.0  # seconds (settings.go:33)
    batch_idle_duration: float = 1.0  # seconds (settings.go:34)
    # None disables the unregistered-machine reaper (settings.go:35-37,86-91:
    # an empty ConfigMap value nils the pointer)
    ttl_after_not_registered: Optional[float] = 15 * 60.0
    drift_enabled: bool = False  # feature gate (settings.go:44)
    # 0 = unbounded (the reference behavior). A positive cap bounds the pods
    # one provisioning pass solves (oldest first; the rest re-enter the next
    # window immediately): under sustained churn an unbounded pass re-batches
    # the WHOLE backlog, so any stall inflates the batch into a new pow2 item
    # bucket — a fresh solver geometry and (on first sight) an XLA compile —
    # which stalls the loop further. The cap pins steady-state passes to a
    # stable geometry, which is also what keeps the incremental delta
    # re-solve path's resident verdict tensor reusable across solves.
    batch_max_pods: int = 0

    @classmethod
    def from_config_map(cls, data: Dict[str, str]) -> "Settings":
        """Parse the settings ConfigMap data (settings.go:53-68). Raises
        ValueError on malformed durations/booleans and on values that fail
        Validate() (settings.go:69-85) — batch windows are required-positive,
        the registration TTL may be empty (disabled) but not negative."""
        s = cls()
        if "batchMaxDuration" in data:
            s.batch_max_duration = _parse_duration(data["batchMaxDuration"])
        if "batchIdleDuration" in data:
            s.batch_idle_duration = _parse_duration(data["batchIdleDuration"])
        if "ttlAfterNotRegistered" in data:
            raw = data["ttlAfterNotRegistered"]
            s.ttl_after_not_registered = None if raw == "" else _parse_duration(raw)
        if "featureGates.driftEnabled" in data:
            raw = data["featureGates.driftEnabled"].lower()
            if raw not in ("true", "false"):
                raise ValueError(f"featureGates.driftEnabled: not a boolean: {raw!r}")
            s.drift_enabled = raw == "true"
        if "batchMaxPods" in data:
            s.batch_max_pods = int(data["batchMaxPods"])
        if s.batch_max_pods < 0:
            raise ValueError("batchMaxPods cannot be negative")
        if s.batch_max_duration <= 0:
            raise ValueError("batchMaxDuration cannot be negative")
        if s.batch_idle_duration <= 0:
            raise ValueError("batchIdleDuration cannot be negative")
        if s.ttl_after_not_registered is not None and s.ttl_after_not_registered <= 0:
            raise ValueError("ttlAfterNotRegistered cannot be negative")
        return s


def _parse_duration(value: str) -> float:
    """Parse a Go-style duration string ("10s", "1m30s", "500ms"); rejects
    malformed input like Go's time.ParseDuration."""
    import re

    value = value.strip()
    unit_re = r"[0-9]+(?:\.[0-9]*)?(?:h|m(?!s)|s|ms|us|ns)"
    if not re.fullmatch(f"(?:{unit_re})+", value):
        raise ValueError(f"cannot parse duration {value!r}")
    matches = re.findall(r"([0-9]+(?:\.[0-9]*)?)(h|m(?!s)|s|ms|us|ns)", value)
    unit_seconds = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}
    return sum(float(n) * unit_seconds[u] for n, u in matches)


_current = Settings()


def current() -> Settings:
    return _current


def set_current(settings: Settings) -> None:
    global _current
    _current = settings
