"""Well-known / restricted label domains, capacity types, annotations.

Mirrors reference pkg/apis/v1alpha5/labels.go:26-135.
"""
from __future__ import annotations

from karpenter_core_tpu.kube.objects import (
    LABEL_ARCH_STABLE,
    LABEL_FAILURE_DOMAIN_BETA_REGION,
    LABEL_FAILURE_DOMAIN_BETA_ZONE,
    LABEL_HOSTNAME,
    LABEL_INSTANCE_TYPE_BETA,
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_OS_STABLE,
    LABEL_TOPOLOGY_REGION,
    LABEL_TOPOLOGY_ZONE,
)

GROUP = "karpenter.sh"
TESTING_GROUP = "testing.karpenter.sh"
COMPATIBILITY_GROUP = "compatibility.karpenter.sh"

ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

PROVISIONER_NAME_LABEL_KEY = f"{GROUP}/provisioner-name"
# pod label naming the tenant a workload bills to (ISSUE 16): the
# provisioner reads it to attribute admission-to-bind latency and solver
# cost per tenant. NOT a scheduling constraint — purely attribution.
TENANT_LABEL_KEY = f"{GROUP}/tenant"
MACHINE_NAME_LABEL_KEY = f"{GROUP}/machine-name"
LABEL_NODE_INITIALIZED = f"{GROUP}/initialized"
LABEL_CAPACITY_TYPE = f"{GROUP}/capacity-type"

DO_NOT_EVICT_POD_ANNOTATION_KEY = f"{GROUP}/do-not-evict"
DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY = f"{GROUP}/do-not-consolidate"
EMPTINESS_TIMESTAMP_ANNOTATION_KEY = f"{GROUP}/emptiness-timestamp"
VOLUNTARY_DISRUPTION_ANNOTATION_KEY = f"{GROUP}/voluntary-disruption"
VOLUNTARY_DISRUPTION_DRIFTED_VALUE = "drifted"
PROVIDER_COMPATIBILITY_ANNOTATION_KEY = f"{COMPATIBILITY_GROUP}/provider"

TERMINATION_FINALIZER = f"{GROUP}/termination"

# Label domains prohibited by the kubelet or reserved by the framework
# (labels.go:62-67).
RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

# Sub-domains of the restricted domains that are allowed (labels.go:69-76).
LABEL_DOMAIN_EXCEPTIONS = frozenset({"kops.k8s.io", "node.kubernetes.io", TESTING_GROUP})

# Labels in restricted domains the framework understands and can narrow
# (labels.go:78-89). A mutable set: the fake cloudprovider registers extra
# well-known labels like the reference's fake does (fake/instancetype.go:40-46).
WELL_KNOWN_LABELS = {
    PROVISIONER_NAME_LABEL_KEY,
    LABEL_TOPOLOGY_ZONE,
    LABEL_TOPOLOGY_REGION,
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_ARCH_STABLE,
    LABEL_OS_STABLE,
    LABEL_CAPACITY_TYPE,
}


def register_well_known_labels(*keys: str) -> None:
    WELL_KNOWN_LABELS.update(keys)

# Labels that must not be injected on nodes (labels.go:91-96).
RESTRICTED_LABELS = frozenset({EMPTINESS_TIMESTAMP_ANNOTATION_KEY, LABEL_HOSTNAME})

# Aliased label keys normalized into the well-known vocabulary
# (labels.go:98-107).
NORMALIZED_LABELS = {
    LABEL_FAILURE_DOMAIN_BETA_ZONE: LABEL_TOPOLOGY_ZONE,
    "beta.kubernetes.io/arch": LABEL_ARCH_STABLE,
    "beta.kubernetes.io/os": LABEL_OS_STABLE,
    LABEL_INSTANCE_TYPE_BETA: LABEL_INSTANCE_TYPE_STABLE,
    LABEL_FAILURE_DOMAIN_BETA_REGION: LABEL_TOPOLOGY_REGION,
}


def is_restricted_node_label(key: str) -> bool:
    """True if the label should not be injected on nodes (labels.go:120-134).

    Well-known labels ARE restricted here: cloud providers inject them, the
    framework must not synthesize values for them."""
    if key in WELL_KNOWN_LABELS:
        return True
    domain = _label_domain(key)
    if domain in LABEL_DOMAIN_EXCEPTIONS:
        return False
    if any(domain.endswith(d) for d in RESTRICTED_LABEL_DOMAINS):
        return True
    return key in RESTRICTED_LABELS


def is_restricted_label(key: str) -> str | None:
    """Returns an error message if the label may not be used (labels.go:107-115)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label {key} is restricted; specify a well known label or a custom "
            f"label that does not use a restricted domain"
        )
    return None


def _label_domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""
