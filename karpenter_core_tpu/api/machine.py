"""Machine — the launch-intent record for one node.

Mirrors reference pkg/apis/v1alpha5/machine.go:23-42 + machine_status.go:
requirements/resources/taints snapshotted from the scheduling decision; status
carries ProviderID/Capacity/Allocatable plus MachineLaunched / MachineRegistered
/ MachineInitialized conditions managed by the machine lifecycle controller.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_core_tpu.api.provisioner import Condition, KubeletConfiguration, ProviderRef
from karpenter_core_tpu.kube.objects import (
    NodeSelectorRequirement,
    ObjectMeta,
    ResourceList,
    Taint,
)

# condition types (machine_status.go)
CONDITION_MACHINE_LAUNCHED = "MachineLaunched"
CONDITION_MACHINE_REGISTERED = "MachineRegistered"
CONDITION_MACHINE_INITIALIZED = "MachineInitialized"
CONDITION_READY = "Ready"


@dataclass
class MachineResourceRequirements:
    requests: ResourceList = field(default_factory=dict)


@dataclass
class MachineSpec:
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[NodeSelectorRequirement] = field(default_factory=list)
    resources: MachineResourceRequirements = field(default_factory=MachineResourceRequirements)
    kubelet: Optional[KubeletConfiguration] = None
    machine_template_ref: Optional[ProviderRef] = None


@dataclass
class MachineStatus:
    provider_id: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class Machine:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MachineSpec = field(default_factory=MachineSpec)
    status: MachineStatus = field(default_factory=MachineStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @property
    def name(self) -> str:
        return self.metadata.name

    def get_condition(self, ctype: str) -> Optional[Condition]:
        for c in self.status.conditions:
            if c.type == ctype:
                return c
        return None

    def set_condition(self, ctype: str, status: str, reason: str = "", message: str = "") -> None:
        import time

        cond = self.get_condition(ctype)
        if cond is None:
            cond = Condition(type=ctype)
            self.status.conditions.append(cond)
        if cond.status != status:
            cond.last_transition_time = time.time()
        cond.status = status
        cond.reason = reason
        cond.message = message

    def condition_true(self, ctype: str) -> bool:
        cond = self.get_condition(ctype)
        return cond is not None and cond.status == "True"
