"""API validation + defaulting for the Provisioner / Machine CRDs.

Mirrors reference pkg/apis/v1alpha5/provisioner_validation.go (the full rule
set: TTL signs, consolidation exclusivity, provider-xor-providerRef, label
name/value syntax, restricted labels, taint dedup + effects, requirement
operators/values, kubelet eviction-signal + reserved-resource checks) and
machine_validation.go / *_defaults.go (both intentionally empty upstream).

Validation errors are collected, not raised: every function returns a list of
human-readable field errors (the knative apis.FieldError analog); callers that
need an exception use `validate_or_raise`.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.machine import Machine
from karpenter_core_tpu.api.provisioner import KubeletConfiguration, Provisioner, ProvisionerSpec
from karpenter_core_tpu.kube.objects import NodeSelectorRequirement, Taint
from karpenter_core_tpu.utils.resources import parse_quantity

# provisioner_validation.go:35-43
SUPPORTED_NODE_SELECTOR_OPS = frozenset(
    {"In", "NotIn", "Gt", "Lt", "Exists", "DoesNotExist"}
)
# provisioner_validation.go:45-50
SUPPORTED_RESERVED_RESOURCES = frozenset({"cpu", "memory", "ephemeral-storage", "pid"})
# provisioner_validation.go:52-59
SUPPORTED_EVICTION_SIGNALS = frozenset(
    {
        "memory.available",
        "nodefs.available",
        "nodefs.inodesFree",
        "imagefs.available",
        "imagefs.inodesFree",
        "pid.available",
    }
)

TAINT_EFFECTS = frozenset({"NoSchedule", "PreferNoSchedule", "NoExecute", ""})

_NAME_PART = re.compile(r"^[A-Za-z0-9]([A-Za-z0-9._-]*[A-Za-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?(\.[a-z0-9]([a-z0-9-]*[a-z0-9])?)*$"
)
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?$")


class ValidationError(Exception):
    """Aggregated field errors (admission-reject analog)."""

    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


# ---------------------------------------------------------------------------
# k8s name / label syntax (apimachinery util/validation analog)


def is_qualified_name(name: str) -> List[str]:
    """Label/taint key syntax: optional DNS-1123 subdomain prefix + '/' +
    a 63-char alphanumeric name part."""
    errs: List[str] = []
    if not name:
        return ["name part must be non-empty"]
    parts = name.split("/")
    if len(parts) == 1:
        prefix, part = "", parts[0]
    elif len(parts) == 2:
        prefix, part = parts
        if not prefix:
            errs.append("prefix part must be non-empty")
        elif len(prefix) > 253 or not _DNS1123_SUBDOMAIN.match(prefix):
            errs.append(f"prefix part {prefix!r} must be a valid DNS-1123 subdomain")
    else:
        return [f"a qualified name {name!r} must have at most one '/'"]
    if not part:
        errs.append("name part must be non-empty")
    elif len(part) > 63 or not _NAME_PART.match(part):
        errs.append(
            f"name part {part!r} must be 63 characters or less, start and end "
            f"alphanumeric, with '-', '_' or '.' between"
        )
    return errs


def is_valid_label_value(value: str) -> List[str]:
    if value == "":
        return []
    if len(value) > 63 or not _NAME_PART.match(value):
        return [
            f"label value {value!r} must be 63 characters or less, start and end "
            f"alphanumeric, with '-', '_' or '.' between"
        ]
    return []


def is_dns1123_subdomain(value: str) -> List[str]:
    if len(value) > 253 or not _DNS1123_SUBDOMAIN.match(value):
        return [f"{value!r} must be a valid DNS-1123 subdomain"]
    return []


# ---------------------------------------------------------------------------
# requirement validation (provisioner_validation.go ValidateRequirement)


def validate_requirement(req: NodeSelectorRequirement) -> List[str]:
    errs: List[str] = []
    key = api_labels.NORMALIZED_LABELS.get(req.key, req.key)
    if req.operator not in SUPPORTED_NODE_SELECTOR_OPS:
        errs.append(
            f"key {key} has an unsupported operator {req.operator} not in "
            f"{sorted(SUPPORTED_NODE_SELECTOR_OPS)}"
        )
    restricted = api_labels.is_restricted_label(key)
    if restricted is not None:
        errs.append(restricted)
    for err in is_qualified_name(key):
        errs.append(f"key {key} is not a qualified name, {err}")
    for value in req.values:
        for err in is_valid_label_value(value):
            errs.append(f"invalid value {value} for key {key}, {err}")
    if req.operator == "In" and not req.values:
        errs.append(f"key {key} with operator In must have a value defined")
    if req.operator in ("Gt", "Lt"):
        ok = len(req.values) == 1
        if ok:
            try:
                ok = int(req.values[0]) >= 0
            except ValueError:
                ok = False
        if not ok:
            errs.append(
                f"key {key} with operator {req.operator} must have a single "
                f"positive integer value"
            )
    return errs


# ---------------------------------------------------------------------------
# provisioner validation


def validate_provisioner(provisioner: Provisioner) -> List[str]:
    errs: List[str] = []
    name = provisioner.metadata.name
    if not name:
        errs.append("metadata.name: name is required")
    elif len(name) > 63 or not _DNS1123_LABEL.match(name):
        errs.append(f"metadata.name: {name!r} must be a valid DNS-1123 label")
    errs.extend(_validate_spec(provisioner.spec))
    return errs


def _validate_spec(spec: ProvisionerSpec) -> List[str]:
    errs: List[str] = []
    if spec.ttl_seconds_until_expired is not None and spec.ttl_seconds_until_expired < 0:
        errs.append("ttlSecondsUntilExpired: cannot be negative")
    if spec.ttl_seconds_after_empty is not None and spec.ttl_seconds_after_empty < 0:
        errs.append("ttlSecondsAfterEmpty: cannot be negative")
    # TTLSecondsAfterEmpty and consolidation are mutually exclusive
    if (
        spec.consolidation is not None
        and spec.consolidation.enabled
        and spec.ttl_seconds_after_empty is not None
    ):
        errs.append(
            "expected exactly one, got both: ttlSecondsAfterEmpty, consolidation.enabled"
        )
    errs.extend(_validate_provider(spec))
    errs.extend(_validate_labels(spec.labels))
    errs.extend(_validate_taints(spec))
    for i, req in enumerate(spec.requirements):
        if req.key == api_labels.PROVISIONER_NAME_LABEL_KEY:
            errs.append(f"requirements[{i}]: {req.key} is restricted")
        errs.extend(f"requirements[{i}]: {e}" for e in validate_requirement(req))
    if spec.kubelet_configuration is not None:
        errs.extend(
            f"kubeletConfiguration: {e}"
            for e in _validate_kubelet(spec.kubelet_configuration)
        )
    return errs


def _validate_provider(spec: ProvisionerSpec) -> List[str]:
    if spec.provider is not None and spec.provider_ref is not None:
        return ["expected exactly one, got both: provider, providerRef"]
    if spec.provider is None and spec.provider_ref is None:
        return ["expected exactly one, got neither: provider, providerRef"]
    return []


def _validate_labels(labels: Dict[str, str]) -> List[str]:
    errs: List[str] = []
    for key, value in labels.items():
        if key == api_labels.PROVISIONER_NAME_LABEL_KEY:
            errs.append(f"labels: invalid key name {key}, restricted")
        for err in is_qualified_name(key):
            errs.append(f"labels: invalid key name {key}, {err}")
        for err in is_valid_label_value(value):
            errs.append(f"labels[{key}]: invalid value {value}, {err}")
        restricted = api_labels.is_restricted_label(key)
        if restricted is not None:
            errs.append(f"labels: invalid key name {key}, {restricted}")
    return errs


def _validate_taints(spec: ProvisionerSpec) -> List[str]:
    errs: List[str] = []
    seen: set = set()
    for field_name, taints in (("taints", spec.taints), ("startupTaints", spec.startup_taints)):
        for i, taint in enumerate(taints):
            errs.extend(_validate_taint(taint, field_name, i))
            pair = (taint.key, taint.effect)
            if pair in seen:
                errs.append(
                    f"{field_name}[{i}]: duplicate taint Key/Effect pair "
                    f"{taint.key}={taint.effect}"
                )
            seen.add(pair)
    return errs


def _validate_taint(taint: Taint, field_name: str, i: int) -> List[str]:
    errs: List[str] = []
    if not taint.key:
        errs.append(f"{field_name}[{i}]: taint key is required")
    else:
        for err in is_qualified_name(taint.key):
            errs.append(f"{field_name}[{i}]: {err}")
    if taint.value:
        for err in is_valid_label_value(taint.value):
            errs.append(f"{field_name}[{i}]: {err}")
    if taint.effect not in TAINT_EFFECTS:
        errs.append(f"{field_name}[{i}]: invalid effect {taint.effect}")
    return errs


def _validate_kubelet(kc: KubeletConfiguration) -> List[str]:
    errs: List[str] = []
    errs.extend(_validate_eviction_thresholds(kc.eviction_hard, "evictionHard"))
    errs.extend(_validate_eviction_thresholds(kc.eviction_soft, "evictionSoft"))
    errs.extend(_validate_reserved(kc.kube_reserved, "kubeReserved"))
    errs.extend(_validate_reserved(kc.system_reserved, "systemReserved"))
    for k in kc.eviction_soft_grace_period:
        if k not in SUPPORTED_EVICTION_SIGNALS:
            errs.append(f"evictionSoftGracePeriod: invalid key name {k}")
    # soft thresholds and grace periods must pair up exactly
    for k in set(kc.eviction_soft) - set(kc.eviction_soft_grace_period):
        errs.append(
            f"evictionSoft: key {k} does not have a matching evictionSoftGracePeriod"
        )
    for k in set(kc.eviction_soft_grace_period) - set(kc.eviction_soft):
        errs.append(
            f"evictionSoftGracePeriod: key {k} does not have a matching "
            f"evictionSoft threshold value"
        )
    hi, lo = kc.image_gc_high_threshold_percent, kc.image_gc_low_threshold_percent
    if hi is not None and hi < (lo or 0):
        errs.append(
            "imageGCHighThresholdPercent: must be greater than imageGCLowThresholdPercent"
        )
    if kc.max_pods is not None and kc.max_pods < 0:
        errs.append("maxPods: cannot be negative")
    if kc.pods_per_core is not None and kc.pods_per_core < 0:
        errs.append("podsPerCore: cannot be negative")
    return errs


def _validate_reserved(resources: Dict[str, object], field_name: str) -> List[str]:
    errs: List[str] = []
    for k, v in resources.items():
        if k not in SUPPORTED_RESERVED_RESOURCES:
            errs.append(f"{field_name}: invalid key name {k}")
        try:
            if parse_quantity(v) < 0:
                errs.append(f'{field_name}["{k}"]: value cannot be a negative quantity')
        except (ValueError, TypeError):
            errs.append(f'{field_name}["{k}"]: value could not be parsed as a quantity')
    return errs


def _validate_eviction_thresholds(m: Dict[str, str], field_name: str) -> List[str]:
    errs: List[str] = []
    for k, v in m.items():
        if k not in SUPPORTED_EVICTION_SIGNALS:
            errs.append(f"{field_name}: invalid key name {k}")
        if isinstance(v, str) and v.endswith("%"):
            try:
                p = float(v[:-1])
            except ValueError:
                errs.append(
                    f'{field_name}["{k}"]: value could not be parsed as a percentage'
                )
                continue
            if p < 0:
                errs.append(f'{field_name}["{k}"]: percentage values cannot be negative')
            if p > 100:
                errs.append(
                    f'{field_name}["{k}"]: percentage values cannot be greater than 100'
                )
        else:
            try:
                parse_quantity(v)
            except (ValueError, TypeError):
                errs.append(
                    f'{field_name}["{k}"]: value could not be parsed as a quantity'
                )
    return errs


# ---------------------------------------------------------------------------
# machine validation + defaults (machine_validation.go / *_defaults.go: empty
# upstream, kept as explicit parity points)


def validate_machine(machine: Machine) -> List[str]:
    return []


def set_provisioner_defaults(provisioner: Provisioner) -> None:
    return None


def set_machine_defaults(machine: Machine) -> None:
    return None


def validate_or_raise(obj) -> None:
    """Dispatch by kind; raises ValidationError on failure."""
    kind = type(obj).__name__
    errors = {"Provisioner": validate_provisioner, "Machine": validate_machine}.get(
        kind, lambda _: []
    )(obj)
    if errors:
        raise ValidationError(errors)
