"""Runtime scheme — the kind registry (reference pkg/operator/scheme +
pkg/apis/apis.go:19-45).

The reference builds a runtime.Scheme mapping GVKs to Go types and embeds
the CRD manifests; controllers and webhooks look types up through it. Here
the registry maps kind names to the dataclasses in kube.objects, declares
which kinds are namespaced, exposes the embedded CRD manifests (the chart
templates), and lists the webhook-managed resources (apis.go:34-45).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Type

from karpenter_core_tpu.api.machine import Machine
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.kube import objects as k8s

_CRD_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "charts", "karpenter-core-tpu-crd", "templates",
)


class Scheme:
    """kind name -> type registry with namespacing metadata."""

    def __init__(self):
        self._types: Dict[str, Type] = {}
        self._namespaced: Dict[str, bool] = {}

    def register(self, type_: Type, namespaced: bool = True) -> "Scheme":
        self._types[type_.__name__] = type_
        self._namespaced[type_.__name__] = namespaced
        return self

    def type_for(self, kind: str) -> Optional[Type]:
        return self._types.get(kind)

    def new_object(self, kind: str):
        t = self.type_for(kind)
        if t is None:
            raise KeyError(f"kind {kind} is not registered in the scheme")
        return t()

    def recognizes(self, kind: str) -> bool:
        return kind in self._types

    def is_namespaced(self, kind: str) -> bool:
        return self._namespaced.get(kind, True)

    def kinds(self) -> List[str]:
        return sorted(self._types)


def default_scheme() -> Scheme:
    """client-go core types + the karpenter API types (scheme.go:20-33)."""
    s = Scheme()
    # karpenter CRDs (cluster-scoped, apis.go:19-31)
    s.register(Provisioner, namespaced=False)
    s.register(Machine, namespaced=False)
    # core/v1 + storage/v1 + policy/v1 kinds the controllers consume
    s.register(k8s.Pod)
    s.register(k8s.Node, namespaced=False)
    s.register(k8s.Namespace, namespaced=False)
    s.register(k8s.ConfigMap)
    s.register(k8s.Secret)
    s.register(k8s.PersistentVolumeClaim)
    s.register(k8s.PersistentVolume, namespaced=False)
    s.register(k8s.StorageClass, namespaced=False)
    s.register(k8s.CSINode, namespaced=False)
    s.register(k8s.PodDisruptionBudget)
    s.register(k8s.DaemonSet)
    s.register(k8s.Event)
    s.register(k8s.Lease)
    return s


def crd_manifests() -> Dict[str, str]:
    """Embedded CRD yamls (apis.go:22-31 embeds pkg/apis/crds/*.yaml; here
    the chart templates are the single source)."""
    out = {}
    if os.path.isdir(_CRD_DIR):
        for fname in sorted(os.listdir(_CRD_DIR)):
            if fname.endswith(".yaml"):
                with open(os.path.join(_CRD_DIR, fname)) as f:
                    out[fname] = f.read()
    return out


# webhook-managed resources (apis.go:34-45): kinds the admission layer
# defaults + validates
WEBHOOK_RESOURCES = ("Provisioner", "Machine")
