"""Fault-injection (chaos) registry: named, deterministic fault points the
production code consults via maybe_fail(name).

The north star demands a control plane that *degrades, never stalls*
(level-triggered reconciliation, operator.go:154-169) — but until this
module nothing in the repo could PROVE recovery: PR 1's ResilientSolver
hardened the accelerator edge after two bench rounds were lost to a wedged
jax.devices(), and every other edge (apiserver transport, watch streams,
cloud-provider create, the gRPC solver client) failed open-loop. This is
the injection layer the chaos suite (tests/test_chaos_*) arms to drive
faults through a full operator loop and assert pods still schedule.

Discipline (same as obs/tracer.py's disabled path):

  * maybe_fail() on an un-armed registry is ONE dict lookup returning
    immediately — the hooks live permanently on production hot paths
    (every kube CRUD, every machine launch, every solver RPC);
  * faults are DETERMINISTIC: probability rides a per-point seeded RNG, so
    a chaos run replays exactly under a fixed seed;
  * schedules compose: `after` skips the first K calls, `times` injects N
    faults then auto-recovers (the fail-N-then-recover shape the launch
    retry / circuit-breaker tests need), `p` injects at a rate, `latency`
    delays instead of (or before) raising.

Arming is programmatic (tests: arm()/disarm()/reset() or the armed()
context manager) or declarative via the KARPENTER_CHAOS env spec:

    KARPENTER_CHAOS="cloudprovider.create=error:ice,times:3;kube.transport=error:conn,p:0.1,seed:42"

Grammar (see docs/robustness.md):

    spec    := clause (';' clause)*
    clause  := point '=' param (',' param)*
    param   := key ':' value
    keys    := error | p | latency | times | after | seed

Error kinds map to the typed exceptions each edge's hardening classifies:
conn/timeout/transport (kube transport retries), unavailable/deadline
(solver RPC retry + circuit breaker), ice/incompatible (cloud-provider
capacity handling), exhausted (admission-gate shed — RESOURCE_EXHAUSTED),
runtime (generic).
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional, Union

from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.obs.log import get_logger

LOG = get_logger("karpenter.chaos")

CHAOS_INJECTED_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_chaos_injected_total",
    "Faults injected by the chaos registry, by fault point and error kind",
)

# canonical fault-point names — production call sites use these constants so
# a typo'd hook fails review, not silently never fires
KUBE_TRANSPORT = "kube.transport"
CLOUDPROVIDER_CREATE = "cloudprovider.create"
SOLVER_RPC = "solver.rpc"
SOLVER_DEVICE = "solver.device"
# the wedge shape (ISSUE 11): the dispatch HANGS instead of erroring — arm
# with error:none + latency past the watchdog (sleep-past-watchdog) so the
# wedge -> open-breaker -> fallback -> re-admit cycle is drivable in-process
# and in the soak harness; the sleeping thread wakes harmlessly later, which
# is exactly the abandoned-thread shape the supervisor accounting names
SOLVER_DEVICE_HANG = "solver.device.hang"
# the host-process crash shape (ISSUE 12): the sidecar solver host dies
# mid-dispatch (OOM-kill, segfault in the accelerator runtime). The hook
# lives in the PARENT (solver/host.SolverHost.call): an injected fault is
# converted into a SIGKILL of the host's process group, so the drill
# exercises the real crash -> respawn -> warm-recover cycle, not a
# simulated exception
SOLVER_HOST_CRASH = "solver.host.crash"
# queue-full injection at the admission gate (solver/host.AdmissionGate):
# models overload shedding without needing a real burst — arm with
# error:exhausted so callers see the same typed RESOURCE_EXHAUSTED a full
# queue raises
SOLVER_RPC_OVERLOAD = "solver.rpc.overload"
# tenant-flood injection at the admission gate (solver/host.AdmissionGate):
# an armed fault does NOT error the request — the gate re-attributes it to
# one synthetic flooding tenant (CHAOS_FLOOD_TENANT), so arming `p:<frac>`
# mid-churn converts that fraction of live traffic into a flood that must
# trip per-tenant quota/brownout isolation while every real tenant's
# accounting stays clean. Arm with error:exhausted (any kind works; the
# raised fault is swallowed at the hook)
SOLVER_GATE_FLOOD = "solver.gate.flood"
# the segmented pack-scan dispatch (ISSUE 14, TPUSolver._try_segmented):
# an injected fault models a device failure inside the segmented attempt —
# partition kernel, lane dispatch, or merge — and the contract is that the
# solve DEGRADES to the sequential scan (fixup_fraction 1.0), never fails
SOLVER_SEGMENT = "solver.segment"
STATE_WATCH = "state.watch"
# the state-store delta feed the incremental solve path gates on
# (state.Cluster.changes_since): an injected fault models dropped or
# duplicated deltas, and the contract is that the consumer DEGRADES to a
# full re-encode instead of trusting a feed that may have lied
STATE_DIFF = "state.diff"

KNOWN_POINTS = (
    KUBE_TRANSPORT,
    CLOUDPROVIDER_CREATE,
    SOLVER_RPC,
    SOLVER_DEVICE,
    SOLVER_DEVICE_HANG,
    SOLVER_HOST_CRASH,
    SOLVER_RPC_OVERLOAD,
    SOLVER_GATE_FLOOD,
    SOLVER_SEGMENT,
    STATE_WATCH,
    STATE_DIFF,
)


def _err_conn() -> Exception:
    return ConnectionResetError("chaos: injected connection reset")


def _err_timeout() -> Exception:
    return TimeoutError("chaos: injected timeout")


def _err_transport() -> Exception:
    return ConnectionError("chaos: injected transport error")


def _err_unavailable() -> Exception:
    from karpenter_core_tpu.solver.service import SolverUnavailableError

    return SolverUnavailableError("chaos: injected UNAVAILABLE")


def _err_deadline() -> Exception:
    from karpenter_core_tpu.solver.service import SolverDeadlineExceededError

    return SolverDeadlineExceededError("chaos: injected DEADLINE_EXCEEDED")


def _err_ice() -> Exception:
    from karpenter_core_tpu.cloudprovider.types import InsufficientCapacityError

    return InsufficientCapacityError("chaos: injected insufficient capacity")


def _err_incompatible() -> Exception:
    from karpenter_core_tpu.cloudprovider.types import (
        IncompatibleRequirementsError,
    )

    return IncompatibleRequirementsError("chaos: injected incompatibility")


def _err_exhausted() -> Exception:
    from karpenter_core_tpu.solver.service import SolverResourceExhaustedError

    return SolverResourceExhaustedError(
        "chaos: injected RESOURCE_EXHAUSTED (admission queue full)"
    )


def _err_runtime() -> Exception:
    return RuntimeError("chaos: injected fault")


# error-kind name -> zero-arg exception factory (lazy imports: chaos is a
# leaf module every layer hooks into; importing the layers here would cycle)
ERROR_KINDS: Dict[str, Callable[[], Exception]] = {
    "conn": _err_conn,
    "timeout": _err_timeout,
    "transport": _err_transport,
    "unavailable": _err_unavailable,
    "deadline": _err_deadline,
    "ice": _err_ice,
    "incompatible": _err_incompatible,
    "exhausted": _err_exhausted,
    "runtime": _err_runtime,
}


class Fault:
    """One armed fault point. Thread-safe: concurrent reconcile workers hit
    the same point and the schedule (after/times/probability) must count
    globally, not per thread."""

    def __init__(
        self,
        point: str,
        error: Union[str, Exception, type, Callable[[], Exception], None] = "runtime",
        probability: float = 1.0,
        latency: float = 0.0,
        times: Optional[int] = None,
        after: int = 0,
        seed: Optional[int] = None,
    ):
        self.point = point
        self.error = error
        self.probability = float(probability)
        self.latency = float(latency)
        self.times = times
        self.after = int(after)
        self.seed = seed
        self._rng = random.Random(seed if seed is not None else 0)
        self._mu = threading.Lock()
        self.calls = 0  # times maybe_fail consulted this point
        self.injected = 0  # times a fault actually fired

    # -- error construction -------------------------------------------------

    def _kind(self) -> str:
        error = self.error
        if error is None:
            return "latency"
        if isinstance(error, str):
            return error
        if isinstance(error, BaseException):
            return type(error).__name__
        if isinstance(error, type):
            return error.__name__
        return getattr(error, "__name__", "callable")

    def _build_error(self) -> Optional[Exception]:
        error = self.error
        if error is None:  # latency-only fault
            return None
        if isinstance(error, str):
            try:
                factory = ERROR_KINDS[error]
            except KeyError:
                raise ValueError(
                    f"unknown chaos error kind {error!r} "
                    f"(known: {', '.join(sorted(ERROR_KINDS))})"
                ) from None
            return factory()
        if isinstance(error, BaseException):
            return error
        # exception class or zero-arg factory
        return error()

    # -- firing -------------------------------------------------------------

    def fire(self) -> None:
        """Decide + inject. Raises the configured error (after any
        configured latency) when the schedule says this call fails."""
        with self._mu:
            self.calls += 1
            if self.calls <= self.after:
                return
            if self.times is not None and self.injected >= self.times:
                return
            if self.probability < 1.0 and self._rng.random() >= self.probability:
                return
            self.injected += 1
            injected = self.injected
            kind = self._kind()
        CHAOS_INJECTED_TOTAL.inc({"point": self.point, "error": kind})
        # a chaos run's log trail shows exactly which call got the fault
        # (correlated by the bound controller/reconcile fields + trace id)
        LOG.debug(
            "chaos fault injected", point=self.point, kind=kind,
            injected=injected,
        )
        if self.latency > 0.0:
            time.sleep(self.latency)
        err = self._build_error()
        if err is not None:
            raise err

    def __repr__(self) -> str:  # armed-state introspection in tests/debug
        with self._mu:  # counters mutate under _mu; read them there too
            calls, injected = self.calls, self.injected
        return (
            f"Fault({self.point!r}, error={self._kind()!r}, "
            f"p={self.probability}, latency={self.latency}, "
            f"times={self.times}, after={self.after}, seed={self.seed}, "
            f"calls={calls}, injected={injected})"
        )


# the armed set. Read lock-free by maybe_fail (CPython dict reads are
# atomic; arming mid-flight is inherently racy anyway — chaos runs arm
# before starting the loop), written under _ARM_MU.
_ARMED: Dict[str, Fault] = {}
_ARM_MU = threading.Lock()


def maybe_fail(point: str) -> None:
    """The production hook. Un-armed (the permanent production state):
    one dict lookup, no allocation, returns immediately."""
    fault = _ARMED.get(point)
    if fault is None:
        return
    fault.fire()


def arm(
    point: str,
    error: Union[str, Exception, type, Callable[[], Exception], None] = "runtime",
    probability: float = 1.0,
    latency: float = 0.0,
    times: Optional[int] = None,
    after: int = 0,
    seed: Optional[int] = None,
) -> Fault:
    """Arm a fault point; returns the Fault for schedule/counter asserts.
    Re-arming replaces the previous fault at that point."""
    fault = Fault(point, error, probability, latency, times, after, seed)
    with _ARM_MU:
        _ARMED[point] = fault
    return fault


def disarm(point: str) -> Optional[Fault]:
    with _ARM_MU:
        return _ARMED.pop(point, None)


def reset() -> None:
    """Disarm everything (test teardown)."""
    with _ARM_MU:
        _ARMED.clear()


def armed_points() -> Dict[str, Fault]:
    with _ARM_MU:
        return dict(_ARMED)


class armed:
    """Context manager: arm for the duration of a with-block, restoring the
    point's previous state on exit (tests nest chaos scopes safely)."""

    def __init__(self, point: str, **kwargs):
        self.point = point
        self.kwargs = kwargs
        self.fault: Optional[Fault] = None
        self._previous: Optional[Fault] = None

    def __enter__(self) -> Fault:
        with _ARM_MU:
            self._previous = _ARMED.get(self.point)
        self.fault = arm(self.point, **self.kwargs)
        return self.fault

    def __exit__(self, exc_type, exc, tb) -> bool:
        with _ARM_MU:
            if self._previous is None:
                _ARMED.pop(self.point, None)
            else:
                _ARMED[self.point] = self._previous
        return False


# ---------------------------------------------------------------------------
# KARPENTER_CHAOS env spec


def parse_spec(spec: str, default_seed: Optional[int] = None) -> Dict[str, Fault]:
    """Parse the env grammar into {point: Fault} without arming (pure,
    testable). Raises ValueError on malformed clauses — a typo'd chaos spec
    must fail loudly at startup, not silently inject nothing."""
    faults: Dict[str, Fault] = {}
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(f"chaos clause {clause!r} is missing '=' (point=params)")
        point, _, params = clause.partition("=")
        point = point.strip()
        if not point:
            raise ValueError(f"chaos clause {clause!r} has an empty fault point")
        if point not in KNOWN_POINTS:
            # a typo'd point would arm nothing and the chaos run would pass
            # vacuously — the exact silent failure this parser must refuse.
            # (Programmatic arm() stays free-form for tests.)
            raise ValueError(
                f"unknown chaos fault point {point!r} "
                f"(known: {', '.join(KNOWN_POINTS)})"
            )
        kwargs: dict = {}
        for param in params.split(","):
            param = param.strip()
            if not param:
                continue
            if ":" not in param:
                raise ValueError(
                    f"chaos param {param!r} in {clause!r} is missing ':' (key:value)"
                )
            key, _, value = param.partition(":")
            key, value = key.strip(), value.strip()
            if key == "error":
                if value not in ERROR_KINDS and value != "none":
                    raise ValueError(
                        f"unknown chaos error kind {value!r} "
                        f"(known: {', '.join(sorted(ERROR_KINDS))}, none)"
                    )
                kwargs["error"] = None if value == "none" else value
            elif key == "p":
                kwargs["probability"] = float(value)
            elif key == "latency":
                kwargs["latency"] = float(value)
            elif key == "times":
                kwargs["times"] = int(value)
            elif key == "after":
                kwargs["after"] = int(value)
            elif key == "seed":
                kwargs["seed"] = int(value)
            else:
                raise ValueError(
                    f"unknown chaos param key {key!r} "
                    "(known: error, p, latency, times, after, seed)"
                )
        if "seed" not in kwargs and default_seed is not None:
            kwargs["seed"] = default_seed
        faults[point] = Fault(point, **kwargs)
    return faults


def arm_from_env(environ=None) -> Dict[str, Fault]:
    """Arm fault points from KARPENTER_CHAOS (+ KARPENTER_CHAOS_SEED as the
    default per-point seed). Called by entrypoints; a no-op when unset.
    Returns the armed faults."""
    environ = environ if environ is not None else envflags.environ()
    spec = environ.get("KARPENTER_CHAOS", "").strip()
    if not spec:
        return {}
    seed_raw = environ.get("KARPENTER_CHAOS_SEED", "").strip()
    default_seed = int(seed_raw) if seed_raw else None
    faults = parse_spec(spec, default_seed=default_seed)
    with _ARM_MU:
        _ARMED.update(faults)
    return faults


# arming at import mirrors the tracer's KARPENTER_TPU_TRACE hook: any
# entrypoint (operator, solver service, bench, a one-off script) opts into
# chaos uniformly by exporting the spec
arm_from_env()
