"""Persistent XLA compilation cache — kills the cold-start stall.

The solve is ONE fused device program per label geometry, and geometry
bucketing keeps the program count tiny (one program serves every varied
50k-pod batch). That makes a disk cache maximally effective: a solver
restart reloads the compiled executable instead of re-paying the ~2-minute
cold compile (BENCH_r04 measured 125 s), so a restart can't blank
provisioning — the reference's in-process Go solver has zero warmup
(scheduler.go:96) and parity demands the same here.

Wired at boot by the operator (operator/__main__.py), the solver service
container (solver/service.py main), and the bench. Must run BEFORE the
first jit compilation in the process.
"""
from __future__ import annotations

import os
import tempfile
from typing import Optional

from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.obs import reqctx

# compiled-program cache observability: every in-process executable-cache
# lookup (TPUSolver._compiled, SolverService._compiled) records a hit or a
# miss, and a miss's first dispatch — which pays jit trace + XLA compile
# (or a persistent-cache disk load) — records its seconds. These are the
# counters ISSUE 1 charters; the solve-path tracer attaches the same
# hit/miss as a span attribute.
CACHE_HITS = REGISTRY.counter(
    f"{NAMESPACE}_compile_cache_hits",
    "Compiled-executable cache hits, by cache site",
)
CACHE_MISSES = REGISTRY.counter(
    f"{NAMESPACE}_compile_cache_misses",
    "Compiled-executable cache misses (jit trace + compile paid), by cache site",
)
COMPILE_SECONDS = REGISTRY.histogram(
    f"{NAMESPACE}_compile_cache_compile_seconds",
    "Seconds spent in a cache-missing solve's first dispatch (includes jit "
    "trace + XLA compile, or the persistent disk-cache load)",
)


def record_lookup(site: str, hit: bool) -> None:
    """One executable-cache lookup outcome (site: 'tpu_solver'/'service').
    A bound request context adds a tenant label — compile-cost attribution:
    which tenant's request forced the cold compile (ISSUE 16)."""
    (CACHE_HITS if hit else CACHE_MISSES).inc(reqctx.tenant_labels(site=site))


def record_compile_seconds(seconds: float) -> None:
    COMPILE_SECONDS.observe(seconds, reqctx.tenant_labels())


def enable_persistent_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at a disk directory.

    KARPENTER_COMPILE_CACHE_DIR overrides the default
    (<tmp>/karpenter-tpu-xla-cache); set it to "0" / "off" to disable.
    Returns the directory in use, or None when disabled/unavailable.

    GSPMD mesh programs opt OUT of cross-process reuse on the CPU backend
    (their cache keys are process-salted — parallel/specs.SpecLayout
    .cache_salt): XLA:CPU deserialization of multi-device executables is
    nondeterministic, and a reloaded mesh solve flips placements. TPU
    mesh programs and all single-device programs cache normally."""
    env = envflags.raw("KARPENTER_COMPILE_CACHE_DIR")
    if env.lower() in ("0", "off", "disabled"):
        return None
    cache_dir = cache_dir or env or os.path.join(
        tempfile.gettempdir(), "karpenter-tpu-xla-cache"
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # the solve programs are few and large: cache everything, not just
        # compiles above the (1s) default threshold
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:  # noqa: BLE001 — older jax: keep the default
            pass
        return cache_dir
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        return None
