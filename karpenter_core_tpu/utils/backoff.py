"""THE jittered-backoff policies — one implementation for every retry site
(apiserver transport, solver RPC, singleton reconcile loops, launch
retriggers), so cap semantics and herd behavior are tuned in one place.

Two shapes, per the AWS architecture-blog taxonomy the reference's
workqueue rate limiters embody:

  * full_jitter: sleep ~ U(0, min(cap, base * 2^attempt)) — the default
    for bounded retry loops; spreads N clients retrying one blip across
    the whole window.
  * decorrelated_jitter: sleep ~ U(base, prev * 3), capped — for
    long-lived loops (singleton reconcilers) where each client's NEXT
    sleep should depend on its own last sleep, not a shared attempt
    counter, so fleets never re-synchronize.

Plus the budget that bounds how often the shapes get used at all:
:class:`RetryBudget`, a per-key token bucket consulted BEFORE a retry is
attempted — jitter spreads a retry storm out, the budget stops it.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional

_MODULE_RNG = random.Random()


class RetryBudget:
    """Per-key token-bucket retry budget (key = guarded tenant label).

    Each key starts with ``capacity`` tokens, refilled continuously at
    ``refill_per_s``; every retry spends one. A key out of tokens gets NO
    retry — the caller raises the original error immediately, so a shed
    tenant cannot convert rejection into a retry storm while every other
    tenant keeps its own full budget. Keys are expected to be
    guard-admitted tenant labels (bounded set); the unbound-tenant key is
    ``""``.

    The bucket only gates WHETHER a retry happens; the sleep shape (full
    jitter, retry-after hints) is untouched.
    """

    def __init__(self, capacity: float = 10.0, refill_per_s: float = 0.5,
                 clock=time.monotonic):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._clock = clock
        self._mu = threading.Lock()
        # key -> (tokens, last-refill timestamp)
        self._buckets: Dict[str, tuple] = {}
        self.spent_total = 0
        self.denied_total = 0

    def try_spend(self, key: Optional[str], cost: float = 1.0) -> bool:
        """Spend *cost* tokens from *key*'s bucket; False = budget spent,
        do not retry."""
        key = key or ""
        with self._mu:
            now = self._clock()
            tokens, last = self._buckets.get(key, (self.capacity, now))
            tokens = min(
                self.capacity, tokens + (now - last) * self.refill_per_s
            )
            if tokens >= cost:
                self._buckets[key] = (tokens - cost, now)
                self.spent_total += 1
                return True
            self._buckets[key] = (tokens, now)
            self.denied_total += 1
            return False

    def stats(self) -> Dict[str, object]:
        with self._mu:
            now = self._clock()
            return {
                "capacity": self.capacity,
                "refill_per_s": self.refill_per_s,
                "spent_total": self.spent_total,
                "denied_total": self.denied_total,
                "tokens": {
                    key: round(
                        min(self.capacity,
                            tokens + (now - last) * self.refill_per_s), 2
                    )
                    for key, (tokens, last) in self._buckets.items()
                },
            }


def full_jitter(attempt: int, base: float, cap: float,
                rng: Optional[random.Random] = None) -> float:
    """Exponential backoff with full jitter: U(0, min(cap, base*2^attempt)).
    attempt is 0-based (the first RETRY passes 0)."""
    rng = rng or _MODULE_RNG
    return rng.uniform(0.0, min(cap, base * (2 ** attempt)))


def decorrelated_jitter(prev: float, base: float, cap: float,
                        rng: Optional[random.Random] = None) -> float:
    """Decorrelated jitter: U(base, prev*3), capped. Feed the returned
    value back as `prev` on the next failure; reset prev to base on
    success."""
    rng = rng or _MODULE_RNG
    return min(rng.uniform(base, max(prev, base) * 3), cap)
