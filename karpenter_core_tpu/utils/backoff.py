"""THE jittered-backoff policies — one implementation for every retry site
(apiserver transport, solver RPC, singleton reconcile loops, launch
retriggers), so cap semantics and herd behavior are tuned in one place.

Two shapes, per the AWS architecture-blog taxonomy the reference's
workqueue rate limiters embody:

  * full_jitter: sleep ~ U(0, min(cap, base * 2^attempt)) — the default
    for bounded retry loops; spreads N clients retrying one blip across
    the whole window.
  * decorrelated_jitter: sleep ~ U(base, prev * 3), capped — for
    long-lived loops (singleton reconcilers) where each client's NEXT
    sleep should depend on its own last sleep, not a shared attempt
    counter, so fleets never re-synchronize.
"""
from __future__ import annotations

import random
from typing import Optional

_MODULE_RNG = random.Random()


def full_jitter(attempt: int, base: float, cap: float,
                rng: Optional[random.Random] = None) -> float:
    """Exponential backoff with full jitter: U(0, min(cap, base*2^attempt)).
    attempt is 0-based (the first RETRY passes 0)."""
    rng = rng or _MODULE_RNG
    return rng.uniform(0.0, min(cap, base * (2 ** attempt)))


def decorrelated_jitter(prev: float, base: float, cap: float,
                        rng: Optional[random.Random] = None) -> float:
    """Decorrelated jitter: U(base, prev*3), capped. Feed the returned
    value back as `prev` on the next failure; reset prev to base on
    success."""
    rng = rng or _MODULE_RNG
    return min(rng.uniform(base, max(prev, base) * 3), cap)
