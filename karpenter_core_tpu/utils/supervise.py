"""Wedge-proof execution supervisor — shared by the bench stage graph and
the operator's device-dispatch watchdog (ISSUE 11 tentpole).

The accelerator tunnel's observed failure mode is a HANG, not an error:
every bench round since r03 lost its TPU number to a wedged probe, and the
operator's `solve_timeout` thread watchdog could only *abandon* a hung
in-process dispatch. This module is the common machinery both paths now
stand on:

  * ``Heartbeat`` — a FILE a supervised worker touches as it makes
    progress. Staleness is the wedge signal, and it is DISTINCT from slow:
    a worker that is still touching its heartbeat is alive (let it spend
    its budget); one that stopped touching is wedged (kill it now, don't
    burn the rest of the budget waiting).
  * ``ThreadHeartbeat`` — the in-process twin (monotonic clock, no file)
    the ResilientSolver watchdog reads while a device dispatch runs on a
    worker thread; the solver's phase marks touch it via the thread-local
    ``touch_heartbeat()`` hook.
  * ``run_supervised`` — run a command in its OWN process group under a
    hard-kill watchdog (SIGKILL the whole group, so a grandchild holding a
    pipe or a forked helper cannot outlive the kill), with heartbeat-based
    wedge detection, bounded restart-with-backoff, and 8KB env-redacted
    output tails for the post-mortem (`extra.wedge_log`).
  * ``ArtifactStore`` — atomic (write-temp-rename) per-unit-of-work JSON
    artifacts, content-keyed by a config digest, so an interrupted run
    RESUMES instead of restarting: a fresh artifact whose digest matches
    the requested config is done; anything missing, degraded, or produced
    on a fallback backend is re-runnable.
  * ``write_verdict``/``read_verdict`` — the TTL'd verdict file an
    out-of-band health daemon publishes so consumers (bench stages) can
    skip straight to a fallback without each paying a probe timeout.

Everything here is stdlib-only and jax-free: the supervisor must keep
working precisely when the accelerator stack is wedged.
"""
from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

# ---------------------------------------------------------------------------
# heartbeats


class Heartbeat:
    """File-based heartbeat: the worker calls touch() at progress points;
    the supervisor reads age(). The file's mtime is the signal — wall
    clock, because worker and supervisor are different processes and the
    filesystem is the only clock they share.

    touch() optionally carries a LABEL (the current span/phase name, e.g.
    ``solver.phase.device``) written as the file's content, so a wedge
    verdict can name the phase the worker died in instead of just an age
    (ISSUE 15). A label-less touch preserves the previous label — phase
    marks label, routine progress ticks don't."""

    def __init__(self, path: str):
        self.path = path

    def touch(self, label: Optional[str] = None) -> None:
        if label is not None:
            # plain overwrite by design (this module is the audited
            # atomic-write funnel): the label is one short line, a reader
            # catching the torn window degrades to "no label", and a
            # rename-per-touch would churn an inode per phase mark
            with open(self.path, "w") as f:
                f.write(label[:256])
            return
        with open(self.path, "a"):
            os.utime(self.path, None)

    def read_label(self) -> str:
        """The last labeled touch's phase name ('' when none/unreadable)."""
        try:
            with open(self.path, "rb") as f:
                return f.read(512).decode("utf-8", errors="replace").strip()
        except OSError:
            return ""

    def age(self) -> Optional[float]:
        """Seconds since the last touch, or None when never touched."""
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return None
        return max(0.0, time.time() - mtime)


class ThreadHeartbeat:
    """In-process heartbeat for thread watchdogs (ResilientSolver): the
    dispatch thread touches it at phase boundaries, the watchdog thread
    reads the age. Monotonic by default; `clock` is injectable for tests.
    Carries the same optional phase label as the file Heartbeat."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._last: Optional[float] = None
        self._label = ""

    def touch(self, label: Optional[str] = None) -> None:
        with self._mu:
            self._last = self._clock()
            if label is not None:
                self._label = label

    def label(self) -> str:
        with self._mu:
            return self._label

    def age(self) -> Optional[float]:
        with self._mu:
            if self._last is None:
                return None
            return max(0.0, self._clock() - self._last)


# thread-local heartbeat binding: the watchdog binds a heartbeat into the
# worker thread it spawns; deep call sites (TPUSolver phase marks) touch it
# without plumbing the object through every signature. Unbound threads
# no-op — the hook is safe on every path.
_TLS = threading.local()

# process-level heartbeat (solver/host.py): a supervised WORKER PROCESS
# (the solver-host sidecar) registers its file Heartbeat here once at boot,
# and every touch_heartbeat() — from ANY thread — also touches it, so the
# parent's file-staleness watchdog sees the same phase-mark progress the
# in-process thread watchdog does. None (the default) is a no-op: the
# thread-local path's cost is unchanged for every existing caller.
_PROCESS_HB: Optional["Heartbeat"] = None


def set_process_heartbeat(hb) -> None:
    """Register a process-wide heartbeat (file Heartbeat or ThreadHeartbeat
    — anything with touch()) that every touch_heartbeat() call also touches.
    Pass None to unregister."""
    global _PROCESS_HB
    _PROCESS_HB = hb


def process_heartbeat():
    return _PROCESS_HB


def bind_heartbeat(hb: Optional[ThreadHeartbeat]) -> None:
    _TLS.heartbeat = hb


def touch_heartbeat(label: Optional[str] = None) -> None:
    hb = getattr(_TLS, "heartbeat", None)
    if hb is not None:
        hb.touch(label)
    if _PROCESS_HB is not None:
        _PROCESS_HB.touch(label)


def bound_heartbeat() -> Optional[ThreadHeartbeat]:
    return getattr(_TLS, "heartbeat", None)


# ---------------------------------------------------------------------------
# output redaction + tails

_SENSITIVE_MARKERS = ("KEY", "TOKEN", "SECRET", "PASSWORD", "CREDENTIAL",
                      "AUTH", "COOKIE")


def redact_env_text(text: str, environ: Optional[Dict[str, str]] = None) -> str:
    """Scrub environment-variable VALUES out of captured worker output
    before it is persisted into an artifact: any env var whose name looks
    sensitive has its value replaced by ``<redacted:NAME>``. Values under
    6 chars are skipped (too short to be a secret, too likely to collide
    with ordinary text)."""
    if environ is None:
        from karpenter_core_tpu.obs import envflags

        environ = envflags.environ()
    for name, value in environ.items():
        if not value or len(value) < 6:
            continue
        upper = name.upper()
        if any(marker in upper for marker in _SENSITIVE_MARKERS):
            text = text.replace(value, f"<redacted:{name}>")
    return text


def tail_bytes_of(path: str, n: int = 8192) -> str:
    """Last n bytes of a file, decoded leniently ('' when unreadable)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > n:
                f.seek(size - n)
            return f.read(n).decode("utf-8", errors="replace")
    except OSError:
        return ""


# ---------------------------------------------------------------------------
# the process-group supervisor


@dataclass
class SuperviseResult:
    """Outcome of one supervised command (after any restarts).

    ``wedged`` and ``timed_out`` are distinct by contract: wedged means the
    heartbeat went stale (the worker stopped making progress and was
    killed early); timed_out means the budget ran out while the worker was
    still alive (slow, not hung)."""

    ok: bool = False
    rc: Optional[int] = None
    wedged: bool = False
    timed_out: bool = False
    restarts: int = 0
    duration_s: float = 0.0
    stdout: str = ""
    stdout_tail: str = ""
    stderr_tail: str = ""
    note: str = ""
    # the worker heartbeat's last phase label at the kill (ISSUE 15): a
    # wedge verdict names WHERE the worker died, not just how stale it was
    phase: str = ""
    attempts: List[str] = field(default_factory=list)
    # the environment the worker ran with (redaction source): secrets the
    # SUPERVISOR never had must still not leak through the captured tails
    environ: Optional[Dict[str, str]] = None

    def wedge_log(self) -> Dict[str, object]:
        """The post-mortem payload a degraded artifact carries — the last
        8KB of each stream, env-redacted, plus the kill classification."""
        return {
            "note": self.note,
            "wedged": self.wedged,
            "timed_out": self.timed_out,
            "rc": self.rc,
            "restarts": self.restarts,
            "phase": self.phase,
            "stdout_tail": redact_env_text(self.stdout_tail, self.environ),
            "stderr_tail": redact_env_text(self.stderr_tail, self.environ),
        }


def _kill_group(proc: subprocess.Popen) -> None:
    """SIGKILL the child's WHOLE process group: a grandchild that survived
    the child (fork bomb, helper holding a pipe) dies with it."""
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.wait(timeout=30)
    except (subprocess.TimeoutExpired, OSError):
        pass


def _run_once(
    cmd: Sequence[str],
    env: Optional[Dict[str, str]],
    timeout_s: float,
    heartbeat: Optional[Heartbeat],
    stale_after_s: Optional[float],
    poll_s: float,
    tail_n: int,
    workdir: str,
    on_output: Optional[Callable[[str], None]],
) -> SuperviseResult:
    out_path = os.path.join(workdir, "stdout")
    err_path = os.path.join(workdir, "stderr")
    res = SuperviseResult(environ=env)
    start = time.monotonic()
    with open(out_path, "wb") as out_f, open(err_path, "wb") as err_f:
        proc = subprocess.Popen(
            list(cmd), stdout=out_f, stderr=err_f,
            env=env, start_new_session=True,
        )
        deadline = start + timeout_s
        echoed = 0
        try:
            while True:
                try:
                    rc = proc.wait(timeout=poll_s)
                    res.rc = rc
                    res.ok = rc == 0
                    res.note = f"rc={rc}"
                    break
                except subprocess.TimeoutExpired:
                    pass
                if on_output is not None:
                    echoed = _echo_new(err_path, echoed, on_output)
                now = time.monotonic()
                hb_age = heartbeat.age() if heartbeat is not None else None
                if (
                    stale_after_s is not None
                    and heartbeat is not None
                    and (hb_age if hb_age is not None
                         else now - start) >= stale_after_s
                ):
                    res.wedged = True
                    res.phase = heartbeat.read_label()
                    res.note = (
                        f"wedged: heartbeat stale for "
                        f"{hb_age if hb_age is not None else now - start:.0f}s "
                        f"(threshold {stale_after_s:.0f}s)"
                        + (f" during {res.phase}" if res.phase else "")
                        + "; process group killed"
                    )
                    _kill_group(proc)
                    res.rc = proc.poll()
                    break
                if now >= deadline:
                    res.timed_out = True
                    if heartbeat is not None:
                        res.phase = heartbeat.read_label()
                    res.note = (
                        f"timed out: still alive at {timeout_s:.0f}s budget "
                        "(heartbeat fresh — slow, not wedged); "
                        "process group killed"
                    )
                    _kill_group(proc)
                    res.rc = proc.poll()
                    break
        finally:
            if proc.poll() is None:
                _kill_group(proc)
    if on_output is not None:
        _echo_new(err_path, echoed, on_output)
    res.duration_s = time.monotonic() - start
    res.stdout = _read_text(out_path)
    res.stdout_tail = res.stdout[-tail_n:]
    res.stderr_tail = tail_bytes_of(err_path, tail_n)
    return res


def _echo_new(path: str, offset: int, on_output: Callable[[str], None]) -> int:
    """Forward bytes appended to `path` since `offset` (live worker stderr
    streaming to the supervisor's own stderr); returns the new offset."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return offset
    if chunk:
        on_output(chunk.decode("utf-8", errors="replace"))
    return offset + len(chunk)


def _read_text(path: str) -> str:
    try:
        with open(path, "rb") as f:
            return f.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def run_supervised(
    cmd: Sequence[str],
    *,
    env: Optional[Dict[str, str]] = None,
    timeout_s: float,
    heartbeat_path: Optional[str] = None,
    stale_after_s: Optional[float] = None,
    poll_s: float = 0.25,
    max_restarts: int = 0,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 30.0,
    tail_n: int = 8192,
    on_output: Optional[Callable[[str], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> SuperviseResult:
    """Run `cmd` in its own process group under a hard-kill watchdog.

    Liveness has two layers: `timeout_s` is the wall budget (a worker that
    exceeds it is SLOW and killed with ``timed_out=True``); when a
    `heartbeat_path` is given, a heartbeat older than `stale_after_s` —
    or never touched at all within that window — is a WEDGE and kills the
    group early (``wedged=True``). Restart-with-backoff applies to failed
    attempts (nonzero rc, wedge, timeout) up to `max_restarts`; backoff
    doubles from `backoff_base_s`, capped at `backoff_max_s`.

    The returned result is the LAST attempt's, with `restarts` and the
    per-attempt notes accumulated. A fresh heartbeat file is used per
    attempt (the previous attempt's touches must not mask a newly wedged
    restart)."""
    attempts: List[str] = []
    total_start = time.monotonic()
    last: Optional[SuperviseResult] = None
    for attempt in range(max_restarts + 1):
        remaining = timeout_s - (time.monotonic() - total_start)
        if attempt > 0 and remaining <= 0:
            break
        hb = None
        if heartbeat_path is not None:
            # fresh per attempt: unlink so a restart starts un-touched
            try:
                os.unlink(heartbeat_path)
            except OSError:
                pass
            hb = Heartbeat(heartbeat_path)
        with tempfile.TemporaryDirectory(prefix="kct-supervise-") as workdir:
            last = _run_once(
                cmd, env, min(timeout_s, max(1.0, remaining)), hb,
                stale_after_s, poll_s, tail_n, workdir, on_output,
            )
        last.restarts = attempt
        attempts.append(f"attempt {attempt + 1}: {last.note}")
        last.attempts = list(attempts)
        if last.ok:
            break
        if attempt < max_restarts:
            sleep(min(backoff_max_s, backoff_base_s * (2 ** attempt)))
    assert last is not None  # max_restarts >= 0 guarantees one attempt
    last.duration_s = time.monotonic() - total_start
    return last


# ---------------------------------------------------------------------------
# atomic, resumable artifacts


def config_digest(config: Dict[str, object]) -> str:
    """Content key for a unit of work: the sha256 of the canonical JSON of
    its configuration. An artifact is only `fresh` for the exact config
    that produced it — change a knob and the stage re-runs on resume."""
    canon = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def atomic_write_json(path: str, payload: Dict[str, object]) -> None:
    """write-temp-fsync-rename in the destination directory: a reader never
    sees a partial artifact, a crash leaves the previous version intact."""
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class ArtifactStore:
    """One JSON artifact per unit of work (a bench stage), written
    atomically as the unit finishes, keyed by config digest.

    Record schema::

        {"stage": name, "config_digest": d, "degraded": bool,
         "fallback": bool, "error": str|None, "wedge_log": {...}|None,
         "meta": {...}, "data": {...}}

    `degraded` means the unit did NOT produce its data (wedge, crash,
    budget) — a resume re-runs it. `fallback` means it produced complete
    data but on a fallback backend (an involuntary CPU column in a TPU
    round) — a resume re-runs it only when the primary backend is back."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def path(self, stage: str) -> str:
        return os.path.join(self.root, f"{stage}.json")

    def save(
        self,
        stage: str,
        config: Dict[str, object],
        data: Optional[Dict[str, object]],
        *,
        degraded: bool = False,
        fallback: bool = False,
        error: Optional[str] = None,
        wedge_log: Optional[Dict[str, object]] = None,
        meta: Optional[Dict[str, object]] = None,
    ) -> Dict[str, object]:
        record: Dict[str, object] = {
            "stage": stage,
            "config_digest": config_digest(config),
            "degraded": bool(degraded),
            "fallback": bool(fallback),
            "error": error,
            "wedge_log": wedge_log,
            "meta": meta or {},
            "data": data,
        }
        atomic_write_json(self.path(stage), record)
        return record

    def load(self, stage: str) -> Optional[Dict[str, object]]:
        try:
            with open(self.path(stage)) as f:
                record = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(record, dict) or record.get("stage") != stage:
            return None
        return record

    def fresh(self, stage: str, config: Dict[str, object]) -> Optional[Dict[str, object]]:
        """The artifact, iff it matches this config and completed (possibly
        on a fallback backend — the caller decides whether fallback data
        is acceptable for this round)."""
        record = self.load(stage)
        if record is None:
            return None
        if record.get("config_digest") != config_digest(config):
            return None
        if record.get("degraded"):
            return None
        return record

    def stages(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(
            n[:-len(".json")] for n in names
            if n.endswith(".json") and not n.startswith(".")
        )


# ---------------------------------------------------------------------------
# TTL'd health verdicts (the out-of-band device-health daemon's output)


def write_verdict(
    path: str,
    ok: bool,
    note: str = "",
    ttl_s: float = 300.0,
    extra: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Publish a health verdict atomically. `ts` is wall-clock — readers
    are other processes; the filesystem clock is the shared one."""
    verdict: Dict[str, object] = {
        "ok": bool(ok),
        "note": note,
        "ts": time.time(),
        "ttl_s": float(ttl_s),
    }
    if extra:
        verdict.update(extra)
    atomic_write_json(path, verdict)
    return verdict


def read_verdict(path: str) -> Optional[Dict[str, object]]:
    """The verdict, or None when missing, unreadable, or past its TTL —
    a stale verdict is NO verdict (the daemon may itself be wedged)."""
    try:
        with open(path) as f:
            verdict = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(verdict, dict):
        return None
    try:
        age = time.time() - float(verdict["ts"])
        ttl = float(verdict["ttl_s"])
    except (KeyError, TypeError, ValueError):
        return None
    if age > ttl:
        return None
    return verdict
