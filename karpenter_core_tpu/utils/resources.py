"""ResourceList algebra.

Mirrors reference pkg/utils/resources/resources.go (Merge, Subtract, Fits,
MaxResources, Cmp, RequestsForPods with the init-container ceiling,
resources.go:24-170) on plain dict[str, float] resource lists.

Quantities are floats: cpu in cores, memory/storage in bytes, counts for pods
and extended resources. `parse_quantity` accepts k8s quantity strings.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List

ResourceList = Dict[str, float]

_SUFFIXES = {
    "": 1.0,
    "m": 1e-3,
    "k": 1e3,
    "K": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)([a-zA-Z]*)$")


def parse_quantity(value) -> float:
    """Parse a k8s quantity ("100m", "1Gi", "2", 2.5) into a float."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QUANTITY_RE.match(str(value).strip())
    if not m:
        raise ValueError(f"cannot parse quantity {value!r}")
    number, suffix = m.groups()
    if suffix not in _SUFFIXES:
        raise ValueError(f"cannot parse quantity suffix {suffix!r} in {value!r}")
    return float(number) * _SUFFIXES[suffix]


def parse_resource_list(d: Dict[str, object]) -> ResourceList:
    return {k: parse_quantity(v) for k, v in d.items()}


def merge(*resource_lists: ResourceList) -> ResourceList:
    """Sum resource lists key-wise (resources.go Merge)."""
    result: ResourceList = {}
    for rl in resource_lists:
        for name, q in rl.items():
            result[name] = result.get(name, 0.0) + q
    return result


def subtract(lhs: ResourceList, rhs: ResourceList) -> ResourceList:
    """lhs - rhs for keys of lhs (resources.go Subtract: rhs-only keys ignored)."""
    result = dict(lhs)
    for name in lhs:
        result[name] = lhs[name] - rhs.get(name, 0.0)
    return result


def max_resources(*resource_lists: ResourceList) -> ResourceList:
    """Key-wise maximum (resources.go MaxResources)."""
    result: ResourceList = {}
    for rl in resource_lists:
        for name, q in rl.items():
            if name not in result or q > result[name]:
                result[name] = q
    return result


def fits(candidate: ResourceList, total: ResourceList) -> bool:
    """True iff candidate <= total key-wise; any negative total never fits
    (resources.go Fits)."""
    for q in total.values():
        if q < 0:
            return False
    for name, q in candidate.items():
        if q > total.get(name, 0.0):
            return False
    return True


def cmp(lhs: float, rhs: float) -> int:
    return (lhs > rhs) - (lhs < rhs)


def _container_requests(container) -> ResourceList:
    """Limits merged into requests where no request exists
    (resources.go MergeResourceLimitsIntoRequests)."""
    requests = dict(container.resources.requests)
    for name, q in container.resources.limits.items():
        requests.setdefault(name, q)
    return requests


def ceiling_requests(pod) -> ResourceList:
    """max(sum of containers, max of init containers) — resources.go Ceiling."""
    total: ResourceList = {}
    for c in pod.spec.containers:
        total = merge(total, _container_requests(c))
    for c in pod.spec.init_containers:
        total = max_resources(total, _container_requests(c))
    return total


def ceiling_limits(pod) -> ResourceList:
    total: ResourceList = {}
    for c in pod.spec.containers:
        total = merge(total, dict(c.resources.limits))
    for c in pod.spec.init_containers:
        total = max_resources(total, dict(c.resources.limits))
    return total


def requests_for_pods(*pods) -> ResourceList:
    """Total requests incl. a "pods" count entry (resources.go RequestsForPods)."""
    merged = merge(*[ceiling_requests(p) for p in pods])
    merged["pods"] = float(len(pods))
    return merged


def limits_for_pods(*pods) -> ResourceList:
    merged = merge(*[ceiling_limits(p) for p in pods])
    merged["pods"] = float(len(pods))
    return merged


def is_zero(rl: ResourceList) -> bool:
    return all(v == 0 for v in rl.values())


def resource_names(resource_lists: Iterable[ResourceList]) -> List[str]:
    names = set()
    for rl in resource_lists:
        names.update(rl)
    return sorted(names)


def to_string(rl: ResourceList) -> str:
    if not rl:
        return "{}"
    return ", ".join(f"{k}={rl[k]:g}" for k in sorted(rl))
