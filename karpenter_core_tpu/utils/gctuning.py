"""Long-lived-server CPython GC tuning, shared by the operator process and
the benchmark.

The solver's decode allocates tens of thousands of short-lived objects per
Solve (pod lists, SolvedMachines); with default thresholds a gen-2
collection eventually lands INSIDE a solve and pauses decode for
100-300 ms — the dominant p50->p99 source once encode is pipelined off the
critical path. The standard server remedy (applied by e.g. Instagram's and
many asyncio deployments) is to freeze the warmed baseline out of collector
scans and widen gen-2's threshold; garbage from each reconcile loop is
still collected promptly by gen-0/1.

The reference sets a GOGC-equivalent soft memory limit at operator start
(operator.go:84-88 via --memory-limit); this is the CPython analog.
"""
import contextlib
import gc
import threading

_applied = False
_pause_lock = threading.Lock()
_pause_depth = 0
_pause_reenable = False
_pause_since = 0.0
# under SUSTAINED overlapping solves (the gRPC service's worker pool) the
# depth may never return to zero, which would leave cyclic GC off for the
# process lifetime; past this span a window EXIT runs an explicit collect
# (gc.collect works while disabled) so cyclic garbage stays bounded
MAX_DEFERRED_SPAN_S = 30.0


@contextlib.contextmanager
def gc_paused():
    """Defer cyclic GC for one latency-critical window (a Solve): even with
    the widened gen-2 threshold, a collection pass scanning the live 50k-pod
    batch costs 100-300 ms when it lands mid-solve — measured as the
    dominant p50->p99 e2e tail source (BENCH r5 tail attribution: p99 run
    +295 ms of host time at flat device time). Refcounting still frees
    acyclic garbage immediately; cyclic garbage waits until a window closes.
    Nested/concurrent use is safe via a process-wide depth counter: GC
    re-enables only when the LAST window closes (the gRPC service runs 4
    solve workers concurrently — an inner exit must not re-enable GC under
    another thread's window), and sustained overlap is bounded by an
    explicit collect on window exits past MAX_DEFERRED_SPAN_S."""
    import time

    global _pause_depth, _pause_reenable, _pause_since
    with _pause_lock:
        if _pause_depth == 0:
            _pause_reenable = gc.isenabled()
            _pause_since = time.monotonic()
            gc.disable()
        _pause_depth += 1
    try:
        yield
    finally:
        collect_now = False
        with _pause_lock:
            _pause_depth -= 1
            if _pause_depth == 0:
                if _pause_reenable:
                    gc.enable()
            elif time.monotonic() - _pause_since > MAX_DEFERRED_SPAN_S:
                # overlapping windows have kept GC off too long: pay one
                # collection on THIS exiting solve's thread (off the other
                # threads' critical windows is impossible process-wide, but
                # unbounded deferral risks OOM — bound it)
                _pause_since = time.monotonic()
                collect_now = True
        if collect_now:
            gc.collect()


def apply_server_gc_tuning(gen2_threshold: int = 100) -> None:
    """Freeze the current (warmed) object graph into the permanent
    generation and widen gen-2's collection threshold. Call AFTER process
    warmup — imports done, compiled-program caches populated — so the
    frozen set covers the long-lived baseline. Idempotent."""
    global _applied
    gc.collect()
    gc.freeze()
    if not _applied:
        a0, a1, _ = gc.get_threshold()
        gc.set_threshold(a0, a1, gen2_threshold)
        _applied = True
