"""Long-lived-server CPython GC tuning, shared by the operator process and
the benchmark.

The solver's decode allocates tens of thousands of short-lived objects per
Solve (pod lists, SolvedMachines); with default thresholds a gen-2
collection eventually lands INSIDE a solve and pauses decode for
100-300 ms — the dominant p50->p99 source once encode is pipelined off the
critical path. The standard server remedy (applied by e.g. Instagram's and
many asyncio deployments) is to freeze the warmed baseline out of collector
scans and widen gen-2's threshold; garbage from each reconcile loop is
still collected promptly by gen-0/1.

The reference sets a GOGC-equivalent soft memory limit at operator start
(operator.go:84-88 via --memory-limit); this is the CPython analog.
"""
import gc

_applied = False


def apply_server_gc_tuning(gen2_threshold: int = 100) -> None:
    """Freeze the current (warmed) object graph into the permanent
    generation and widen gen-2's collection threshold. Call AFTER process
    warmup — imports done, compiled-program caches populated — so the
    frozen set covers the long-lived baseline. Idempotent."""
    global _applied
    gc.collect()
    gc.freeze()
    if not _applied:
        a0, a1, _ = gc.get_threshold()
        gc.set_threshold(a0, a1, gen2_threshold)
        _applied = True
