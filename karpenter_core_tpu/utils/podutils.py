"""Pod predicate helpers (reference pkg/utils/pod/scheduling.go)."""
from __future__ import annotations

from karpenter_core_tpu.kube.objects import Pod


def is_scheduled(pod: Pod) -> bool:
    return pod.spec.node_name != ""


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Succeeded", "Failed")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_owned_by_daemonset(pod: Pod) -> bool:
    return any(o.kind == "DaemonSet" for o in pod.metadata.owner_references)


def is_owned_by_node(pod: Pod) -> bool:
    return any(o.kind == "Node" for o in pod.metadata.owner_references)


def failed_to_schedule(pod: Pod) -> bool:
    """PodScheduled condition False with reason Unschedulable."""
    for cond in pod.status.conditions:
        if cond.type == "PodScheduled" and cond.status == "False" and cond.reason == "Unschedulable":
            return True
    return False


def is_provisionable(pod: Pod) -> bool:
    """The pod needs a new node (pod/scheduling.go IsProvisionable)."""
    return (
        not is_scheduled(pod)
        and not is_terminal(pod)
        and not is_terminating(pod)
        and failed_to_schedule(pod)
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
    )


def has_pod_anti_affinity(pod: Pod) -> bool:
    """True if the pod has any required pod anti-affinity term."""
    return (
        pod.spec.affinity is not None
        and pod.spec.affinity.pod_anti_affinity is not None
        and len(pod.spec.affinity.pod_anti_affinity.required) > 0
    )


def has_required_pod_affinity(pod: Pod) -> bool:
    return (
        pod.spec.affinity is not None
        and pod.spec.affinity.pod_affinity is not None
        and len(pod.spec.affinity.pod_affinity.required) > 0
    )


def tolerates_unschedulable_taint(pod: Pod) -> bool:
    from karpenter_core_tpu.kube.objects import TAINT_NODE_UNSCHEDULABLE, Taint

    taint = Taint(key=TAINT_NODE_UNSCHEDULABLE, effect="NoSchedule")
    return any(t.tolerates_taint(taint) for t in pod.spec.tolerations)


def is_evictable(pod: Pod) -> bool:
    return not is_terminal(pod)


def has_do_not_evict(pod: Pod) -> bool:
    from karpenter_core_tpu.api.labels import DO_NOT_EVICT_POD_ANNOTATION_KEY

    return pod.metadata.annotations.get(DO_NOT_EVICT_POD_ANNOTATION_KEY) == "true"


def clone_for_simulation(pod):
    """Shallow clone for scheduling simulation: fresh Pod + PodSpec with
    node_name cleared, everything beneath shared read-only. The reference's
    simulateScheduling passes the SAME pod pointers (helpers.go:41-105);
    the deep clone this replaces spent more host time than the device
    ladder it fed at 10k-pod replans."""
    import copy as _copy

    clone = _copy.copy(pod)
    clone.spec = _copy.copy(pod.spec)
    clone.spec.node_name = ""
    return clone
