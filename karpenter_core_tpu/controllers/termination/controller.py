"""Node termination finalizer: cordon -> drain -> terminate.

Mirrors reference pkg/controllers/termination/controller.go:50-98: when a
Node with the termination finalizer is deleted, cordon it, drain (requeueing
while NodeDrainError persists), then delete the instance and remove the
finalizer.
"""
from __future__ import annotations

from typing import Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.controllers.machine.terminator import NodeDrainError, Terminator
from karpenter_core_tpu.kube.objects import Node
from karpenter_core_tpu.metrics.registry import NODES_TERMINATED


class TerminationController:
    def __init__(self, kube_client, terminator: Terminator, cluster=None, recorder=None):
        self.kube_client = kube_client
        self.terminator = terminator
        self.cluster = cluster
        self.recorder = recorder

    def reconcile(self, node: Node) -> Optional[float]:
        if node.metadata.deletion_timestamp is None:
            return None
        return self.finalize(node)

    def finalize(self, node: Node) -> Optional[float]:
        """controller.go:64-86 — a no-op without the finalizer (:65-67)."""
        if api_labels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return None
        self.terminator.cordon(node)
        try:
            self.terminator.drain(node)
        except NodeDrainError as e:
            if self.recorder:
                self.recorder.node_failed_to_drain(node, str(e))
            return 1.0  # requeue while draining
        self.terminator.terminate_node(node)
        NODES_TERMINATED.inc({"reason": "terminated"})
        if self.cluster is not None:
            self.cluster.delete_node(node.metadata.name)
        return None
