"""Inject PVC zone requirements into pod node-affinity.

Mirrors reference pkg/controllers/provisioning/volumetopology.go:36-120: for
each PVC-backed volume, derive the viable zones from the bound PV's node
affinity or the StorageClass allowed-topologies, and AND them into EVERY
required node-selector term so preference relaxation can't drop them.
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_core_tpu.kube.objects import (
    Affinity,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
)


class VolumeTopology:
    def __init__(self, kube_client):
        self.kube_client = kube_client

    def inject(self, pod: Pod) -> Pod:
        requirements = self._get_requirements(pod)
        if not requirements:
            return pod
        if pod.spec.affinity is None:
            pod.spec.affinity = Affinity()
        if pod.spec.affinity.node_affinity is None:
            pod.spec.affinity.node_affinity = NodeAffinity()
        if not pod.spec.affinity.node_affinity.required:
            pod.spec.affinity.node_affinity.required = [NodeSelectorTerm()]
        # zonal requirements are AND-ed into every OR term (volumetopology.go:53-60)
        for term in pod.spec.affinity.node_affinity.required:
            term.match_expressions.extend(requirements)
        return pod

    def validate(self, pod: Pod) -> Optional[str]:
        """validatePersistentVolumeClaims (volumetopology.go:146-199): returns
        an error string when a volume references a missing PVC / PV /
        StorageClass (including ephemeral claim templates) — such pods are
        ignored by GetPendingPods rather than failing the whole batch."""
        for volume in pod.spec.volumes:
            storage_class_name = None
            volume_name = ""
            if volume.persistent_volume_claim is not None:
                pvc = self.kube_client.get(
                    "PersistentVolumeClaim",
                    pod.metadata.namespace,
                    volume.persistent_volume_claim.claim_name,
                )
                if pvc is None:
                    return (
                        f"persistent volume claim "
                        f"{volume.persistent_volume_claim.claim_name!r} not found"
                    )
                storage_class_name = pvc.spec.storage_class_name
                volume_name = pvc.spec.volume_name
            elif volume.ephemeral is not None:
                storage_class_name = volume.ephemeral.storage_class_name
            if storage_class_name:
                if self.kube_client.get("StorageClass", "", storage_class_name) is None:
                    return f"storage class {storage_class_name!r} not found"
            if volume_name:
                if self.kube_client.get("PersistentVolume", "", volume_name) is None:
                    return f"persistent volume {volume_name!r} not found"
        return None

    def _get_requirements(self, pod: Pod) -> List[NodeSelectorRequirement]:
        requirements: List[NodeSelectorRequirement] = []
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim is None:
                continue
            pvc = self.kube_client.get(
                "PersistentVolumeClaim",
                pod.metadata.namespace,
                volume.persistent_volume_claim.claim_name,
            )
            if pvc is None:
                continue
            reqs = self._from_bound_pv(pvc) or self._from_storage_class(pvc)
            if reqs:
                requirements.extend(reqs)
        return requirements

    def _from_bound_pv(self, pvc) -> Optional[List[NodeSelectorRequirement]]:
        if not pvc.spec.volume_name:
            return None
        pv = self.kube_client.get("PersistentVolume", "", pvc.spec.volume_name)
        if pv is None or not pv.spec.node_affinity_required:
            return None
        out = []
        for term in pv.spec.node_affinity_required:
            out.extend(term.match_expressions)
        return out or None

    def _from_storage_class(self, pvc) -> Optional[List[NodeSelectorRequirement]]:
        if not pvc.spec.storage_class_name:
            return None
        sc = self.kube_client.get("StorageClass", "", pvc.spec.storage_class_name)
        if sc is None or not sc.allowed_topologies:
            return None
        out = []
        for term in sc.allowed_topologies:
            for expr in term.match_label_expressions:
                out.append(NodeSelectorRequirement(expr.key, "In", list(expr.values)))
        return out or None
