"""Provisioner — the singleton provisioning loop.

Mirrors reference pkg/controllers/provisioning/provisioner.go: batch pending
pods -> snapshot cluster -> solve -> launch machines in parallel -> create
Node objects eagerly -> nominate. The solve is pluggable: the TPU tensor
solver (solver.TPUSolver) by default with the host GreedySolver as fallback —
the Solver boundary the reference lacks (its Solve is in-process,
provisioner.go:301).
"""
from __future__ import annotations

import concurrent.futures
import contextlib
import copy
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu import chaos
from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.provisioner import Provisioner as ProvisionerCRD
from karpenter_core_tpu.cloudprovider.icecache import ICECache
from karpenter_core_tpu.cloudprovider.types import (
    IncompatibleRequirementsError,
    InsufficientCapacityError,
)
from karpenter_core_tpu.controllers.provisioning.batcher import Batcher
from karpenter_core_tpu.controllers.provisioning.volumetopology import VolumeTopology
from karpenter_core_tpu.kube.objects import Node, NodeStatus, Pod
from karpenter_core_tpu.metrics.registry import NAMESPACE, NODES_CREATED, REGISTRY
from karpenter_core_tpu.obs import TRACER
from karpenter_core_tpu.obs import reqctx
from karpenter_core_tpu.obs.log import get_logger
from karpenter_core_tpu.solver.tpu_solver import GreedySolver, SolvedMachine, SolveResult
from karpenter_core_tpu.utils import podutils

LOG = get_logger("karpenter.provisioning")

LAUNCH_FAILURES = REGISTRY.counter(
    f"{NAMESPACE}_launch_failures_total",
    "Machine launches that failed, by failure class (insufficient_capacity /"
    " transient / error)",
)
LAUNCH_RESOLVE_RETRIGGERS = REGISTRY.counter(
    f"{NAMESPACE}_launch_resolve_retriggers_total",
    "Batcher re-triggers after retryable launch failures: the residual pods"
    " re-solve against an ICE-masked universe instead of spinning on the"
    " offering the cloud just rejected",
)
# the soak SLOs (hack/soak.py) read these from real exposition, not
# bench-side timing: admission -> bind is the pod-visible provisioning
# latency (pod creation to capacity decision — a machine launched for it
# or an existing node nominated), pending_pods the batch-queue depth each
# reconcile observed
ADMISSION_TO_BIND = REGISTRY.histogram(
    f"{NAMESPACE}_admission_to_bind_seconds",
    "Pod admission (creationTimestamp) to bind decision (machine launched /"
    " existing node nominated) latency, observed by the provisioning loop",
)
PENDING_PODS = REGISTRY.gauge(
    f"{NAMESPACE}_pending_pods",
    "Provisionable pending pods the last provisioning pass batched",
)


@dataclass
class LaunchOptions:
    record_pod_nomination: bool = False
    reason: str = "provisioning"


class ProvisioningController:
    """provisioner.go:62-126."""

    def __init__(
        self,
        kube_client,
        cloud_provider,
        cluster,
        recorder=None,
        solver=None,
        fallback_solver=None,
        clock=time.time,
    ):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.recorder = recorder
        # wall clock, compared against pod creationTimestamps for the
        # admission->bind histogram (same convention as state.Cluster)
        self.clock = clock
        self.solver = solver or GreedySolver()
        self.fallback_solver = fallback_solver or GreedySolver()
        self.batcher = Batcher()
        self.volume_topology = VolumeTopology(kube_client)
        # exhausted offerings observed at launch; masked from the universe
        # the next Solve sees so residual pods re-place elsewhere
        self.ice_cache = ICECache()
        # launch-retry pacing: consecutive retryable-failure count and the
        # monotonic deadline of the next scheduled re-trigger (None = none
        # pending) — the workqueue-backoff analog, so a persistently
        # failing launch re-solves on a growing jittered interval instead
        # of burning a full solve every batch window
        self._launch_retry_failures = 0
        self._launch_retry_at: Optional[float] = None
        # guards the retry-pacing fields above and _last_solve_inputs:
        # step()-driven reconciles (tests, soak driver) overlap the
        # singleton loop's, and the failure-explanation probe reads from
        # controller threads (racewatch, ISSUE 13)
        self._mu = threading.Lock()
        # (provisioners, instance_types) the LAST solve saw — the failure-
        # explanation probe reads them so it never races provisioner churn
        self._last_solve_inputs: Tuple[list, dict] = ([], {})
        # bind feed: callables(pod, node_name) invoked at each capacity
        # decision (machine launched / existing node nominated). The soak
        # driver registers here to play kubelet/kube-scheduler — recorder
        # nomination events are deduped + rate-limited, so they cannot
        # serve as a faithful binding feed. Best-effort: a listener fault
        # never breaks the reconcile that fed it.
        self.bind_listeners: List = []
        # admission->bind observes each pod ONCE, at its FIRST capacity
        # decision: a nominated-but-not-yet-bound pod re-enters every batch
        # window until the external scheduler binds it, and re-observing it
        # would turn the SLO histogram into a re-nomination-streak counter
        # (bounded LRU of pod uids; uid, not name — a delete+recreate is a
        # new admission)
        self._admission_observed: OrderedDict = OrderedDict()
        self.MAX_ADMISSION_OBSERVED = 8192

    # -- reconcile loop ----------------------------------------------------

    def reconcile(self, wait_timeout: float = 0.0) -> int:
        """One pass: returns the number of machines launched
        (provisioner.go:105-126)."""
        if wait_timeout is not None:
            self._maybe_fire_launch_retry()
            if not self.batcher.wait(timeout=wait_timeout):
                return 0
        # the reconcile ROOT span: schedule (solver.solve nests under it)
        # and launch both land in the same trace, so one Perfetto timeline
        # shows batch -> solve phases -> machine launches end to end
        with TRACER.span("provisioner.reconcile") as sp:
            created = self._reconcile_traced(sp)
        return created

    def _reconcile_traced(self, sp) -> int:
        result = self.schedule()
        if result is None:
            return 0
        sp.set(
            machines=len(result.new_machines),
            existing=len(result.existing_assignments),
            failed=len(result.failed_pods),
        )
        with TRACER.span("provisioner.launch", machines=len(result.new_machines)):
            names, errors = self._launch_machines_with_errors(
                result.new_machines, LaunchOptions(record_pod_nomination=True)
            )
        created = sum(1 for n in names if n)
        # admission->bind SLO: a pod is "bound" when the loop made its
        # capacity decision — its machine launched, or (below) an existing
        # node was nominated for it
        now = self.clock()
        for machine, name in zip(result.new_machines, names):
            if name:
                for pod in machine.pods:
                    self._observe_bind(pod, now)
                    self._notify_bind(pod, name)
        for state_node, pods in result.existing_assignments:
            for pod in pods:
                self._observe_bind(pod, now)
                self._notify_bind(pod, state_node.name())
        if created or errors or result.failed_pods:
            LOG.info(
                "provisioning pass",
                machines=len(result.new_machines), launched=created,
                launch_errors=len(errors),
                existing=len(result.existing_assignments),
                failed_pods=len(result.failed_pods), rounds=result.rounds,
            )
        if any(self._launch_retryable(e) for e in errors):
            # level-triggered launch retry: the failed machines' pods are
            # still pending, the exhausted offerings are now ICE-masked —
            # schedule a re-trigger so a later reconcile re-SOLVES the
            # residual pods against the masked universe instead of waiting
            # for an unrelated pod event. Paced by jittered exponential
            # backoff on consecutive failures (workqueue-requeue analog): a
            # PERSISTENTLY failing launch must not burn a full solve every
            # batch window.
            LAUNCH_RESOLVE_RETRIGGERS.inc()
            with self._mu:
                self._launch_retry_failures += 1
                failures = self._launch_retry_failures
            self._schedule_launch_retry(failures)
        else:
            with self._mu:
                self._launch_retry_failures = 0
            if result.failed_pods:
                # pods left unplaced while offerings are ICE-masked: arm
                # ONE re-trigger at the earliest cache-entry expiry (masked
                # capacity cannot return any sooner) so the batch re-solves
                # then instead of either polling a full solve per window or
                # waiting for an unrelated pod event
                wait = self.ice_cache.next_expiry_in()
                if wait is not None:
                    self._schedule_launch_retry_in(wait + 0.05)
        if created:
            NODES_CREATED.inc({"reason": "provisioning"}, created)
        # nominate existing-node placements (scheduler.go:143-153)
        for state_node, pods in result.existing_assignments:
            self.cluster.nominate_node_for_pod(state_node.name())
            if self.recorder:
                for pod in pods:
                    self.recorder.nominate_pod(pod, state_node.name())
        if result.failed_pods and self.recorder:
            # the host scheduler records exact per-pod causes in
            # result.errors; the device solver reports WHICH pods failed
            # but not why, so the remaining gaps are re-checked against
            # the host constraint algebra — incompatible requirements
            # (with typo hints), intolerable taints, or no fitting
            # instance type — like the reference's per-pod solve errors
            # (scheduler.go:96-133 via events.PodFailedToSchedule).
            # Explanation must never cost the reconcile its result:
            # machines are already launched at this point.
            reasons = dict(getattr(result, "errors", None) or {})
            missing = [
                p for p in result.failed_pods
                if not reasons.get(p.metadata.uid)
            ]
            if missing:
                try:
                    reasons.update(self._explain_failures(missing))
                except Exception:  # noqa: BLE001 — events are best-effort
                    pass
            for pod in result.failed_pods:
                self.recorder.pod_failed_to_schedule(
                    pod, reasons.get(pod.metadata.uid) or "unschedulable"
                )
        return created

    def _explain_failures(self, failed: List[Pod]) -> Dict[str, str]:
        """Template-level failure causes for failed pods, keyed by pod uid.
        Probes each weighted template with the host checks the scheduler's
        Machine.Add performs (taints -> requirements -> instance-type fit,
        machine.go:62-107), against the SAME provisioners/instance-types
        snapshot the solve used (stashed by schedule() — re-listing here
        would race provisioner churn). A pod placeable on SOME template
        failed for a batch-level reason (topology, limits, slot budget)
        and keeps the generic message."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.machine import (
            MachineTemplate,
            filter_instance_types_by_requirements,
        )
        from karpenter_core_tpu.scheduling import taints as taints_mod
        from karpenter_core_tpu.scheduling.requirements import Requirements
        from karpenter_core_tpu.utils import resources as resources_util

        reasons: Dict[str, str] = {}
        with self._mu:
            provisioners, instance_types = self._last_solve_inputs
        if not provisioners:
            return reasons
        templates = [
            (MachineTemplate(p), instance_types.get(p.name, []))
            for p in provisioners  # already weight-ordered by schedule()
        ]
        for pod in failed:
            pod_reqs = Requirements.from_pod(pod)
            requests = resources_util.requests_for_pods(pod)
            err_msg = None
            for template, types in templates:
                err = taints_mod.tolerates(template.taints, pod)
                if err is None:
                    merged = Requirements(template.requirements.values())
                    err = merged.compatible(pod_reqs)
                    if err:
                        err = f"incompatible requirements, {err}"
                    else:
                        merged.add(*pod_reqs.values())
                        if not filter_instance_types_by_requirements(
                            types, merged, requests
                        ):
                            err = (
                                f"no instance type satisfied resources "
                                f"{resources_util.to_string(requests)} "
                                f"and requirements {merged!r}"
                            )
                if err is None:
                    err_msg = None
                    break  # placeable here: the failure was batch-level
                err_msg = err
            if err_msg:
                reasons[pod.metadata.uid] = err_msg
        return reasons

    @staticmethod
    def _pod_tenant(pod: Pod) -> Optional[str]:
        """Tenant a pod bills to (karpenter.sh/tenant label), or None."""
        return (pod.metadata.labels or {}).get(api_labels.TENANT_LABEL_KEY)

    def _observe_bind(self, pod: Pod, now: float) -> None:
        uid = pod.metadata.uid or (pod.metadata.namespace, pod.metadata.name)
        if uid in self._admission_observed:
            return
        self._admission_observed[uid] = True
        while len(self._admission_observed) > self.MAX_ADMISSION_OBSERVED:
            self._admission_observed.popitem(last=False)
        ts = getattr(pod.metadata, "creation_timestamp", None)
        if ts:
            # per-tenant admission-to-bind: the POD's own tenant label (not
            # the batch context — bind latency is per-pod), through the
            # cardinality guard; tenant-less pods keep the unlabeled series
            tenant = self._pod_tenant(pod)
            if tenant is not None:
                ADMISSION_TO_BIND.observe(
                    max(now - ts, 0.0),
                    {"tenant": reqctx.TENANTS.admit(tenant)},
                )
            else:
                ADMISSION_TO_BIND.observe(max(now - ts, 0.0))

    def _notify_bind(self, pod: Pod, node_name: str) -> None:
        for listener in self.bind_listeners:
            try:
                listener(pod, node_name)
            except Exception:  # noqa: BLE001 — listeners are best-effort
                LOG.warning(
                    "bind listener failed", pod=pod.metadata.name,
                    node=node_name,
                )

    def trigger(self) -> None:
        self.batcher.trigger()

    # -- scheduling --------------------------------------------------------

    def get_pending_pods(self) -> List[Pod]:
        """Provisionable pods (provisioner.go:152-174); pods failing Validate
        — opted out of Karpenter nodes, invalid affinity requirements, or
        invalid volume references — are ignored (provisioner.go:166-169)."""
        pods = self.kube_client.list("Pod", field_filter=lambda p: p.spec.node_name == "")
        return [
            p
            for p in pods
            if podutils.is_provisionable(p) and self._validate_pod(p) is None
        ]

    def _validate_pod(self, pod: Pod) -> Optional[str]:
        """Provisioner.Validate (provisioner.go:376-434): provisioner-name
        opt-out, affinity-term requirement validity, volume references."""
        from karpenter_core_tpu.api.validation import validate_requirement
        from karpenter_core_tpu.scheduling.requirements import Requirements

        # validateProvisionerNameCanExist (provisioner.go:386-394): a pod
        # that requires the provisioner-name label to NOT exist (e.g. the
        # controller's own replicas) never enters the batch
        for req in Requirements.from_pod(pod).values():
            if (
                req.key == api_labels.PROVISIONER_NAME_LABEL_KEY
                and req.operator() == "DoesNotExist"
            ):
                return (
                    f"configured to not run on a Karpenter provisioned node "
                    f"via {req.key} DoesNotExist requirement"
                )
        # validateAffinity (provisioner.go:408-434): every node-affinity term
        # must carry well-formed requirements
        affinity = pod.spec.affinity
        if affinity is not None and affinity.node_affinity is not None:
            terms = list(affinity.node_affinity.required)
            terms.extend(p.preference for p in affinity.node_affinity.preferred)
            for term in terms:
                for expr in term.match_expressions:
                    errs = validate_requirement(expr)
                    if errs:
                        return "; ".join(errs)
        return self.volume_topology.validate(pod)

    def get_daemonset_pods(self) -> List[Pod]:
        """Synthetic pods from DaemonSet templates (provisioner.go:365-382)."""
        out = []
        for ds in self.kube_client.list("DaemonSet"):
            if ds.pod_template_spec is not None:
                pod = Pod(spec=copy.deepcopy(ds.pod_template_spec))
                pod.metadata.name = f"{ds.metadata.name}-daemon"
                pod.metadata.namespace = ds.metadata.namespace
                out.append(pod)
        return out

    def schedule(self) -> Optional[SolveResult]:
        """provisioner.go:266-302."""
        # nodes in deletion are excluded from the snapshot; pods bound to
        # deleting nodes re-enter the batch (provisioner.go:278-295)
        state_nodes = []
        deleting_nodes = []
        for node in self.cluster.nodes():
            (deleting_nodes if node.is_marked_for_deletion() else state_nodes).append(node)
        pending = self.get_pending_pods()
        for node in deleting_nodes:
            if node.node is not None:
                for pod in self.kube_client.list(
                    "Pod", field_filter=lambda p, n=node: p.spec.node_name == n.name()
                ):
                    if not podutils.is_terminal(pod) and not podutils.is_owned_by_daemonset(pod):
                        reschedule = copy.deepcopy(pod)
                        reschedule.spec.node_name = ""
                        pending.append(reschedule)
        PENDING_PODS.set(float(len(pending)))
        if not pending:
            return None
        from karpenter_core_tpu.api.settings import current

        settings = self.batcher.settings or current()
        # the enforced cap is clamped to the bucket ladder's top rung
        # (Settings.effective_batch_max_pods): a pass larger than the
        # largest tier would mint an unlisted (overflow) solver geometry —
        # an un-prewarmed compile — so it splits instead
        batch_cap = settings.effective_batch_max_pods()
        if batch_cap and len(pending) > batch_cap:
            # bounded pass: solve the OLDEST cap-sized slice and hand the
            # remainder straight to the next window (re-trigger now, not
            # after the idle timeout) — see Settings.batch_max_pods for why
            # an unbounded backlog re-batch compounds its own stall. The
            # re-trigger fires only when the deferred slice holds pods that
            # never got a capacity decision: nominated-but-unbound pods
            # re-enter pending until the external scheduler binds them, and
            # spinning back-to-back passes on ONLY those would re-solve the
            # same decided set forever against a slow/down scheduler.
            pending.sort(key=lambda p: p.metadata.creation_timestamp or 0.0)
            deferred = pending[batch_cap:]
            pending = pending[:batch_cap]
            LOG.info("batch capped", solving=len(pending), deferred=len(deferred))
            if any(
                (p.metadata.uid or (p.metadata.namespace, p.metadata.name))
                not in self._admission_observed
                for p in deferred
            ):
                self.batcher.trigger()
        from karpenter_core_tpu.api.provisioner import order_by_weight

        provisioners = order_by_weight(
            [
                p
                for p in self.kube_client.list("Provisioner")
                if p.metadata.deletion_timestamp is None
            ]
        )
        if not provisioners:
            return None
        # offerings the cloud recently ICE'd are masked so this solve
        # places pods where capacity actually exists (TTL'd: exhaustion is
        # transient, the offering returns when the cache entry expires)
        instance_types = {
            p.name: self.ice_cache.mask(self.cloud_provider.get_instance_types(p))
            for p in provisioners
        }
        # the exact inputs this solve saw, for the failure-explanation
        # probe (re-listing would race provisioner churn); under _mu —
        # step()-driven and loop-driven reconciles can overlap
        with self._mu:
            self._last_solve_inputs = (provisioners, instance_types)
        pending = [self.volume_topology.inject(copy.deepcopy(p)) for p in pending]
        daemonset_pods = self.get_daemonset_pods()
        # operator-reconcile attribution entry point (ISSUE 16): the solve
        # is one batch-level unit of work, billed to the batch's plurality
        # tenant (pod labels; admission-to-bind stays exactly per-pod in
        # _observe_bind). The bind rides through the whole ladder — gate,
        # frame header, child process, flight record, compile cache.
        tenants = [t for t in (self._pod_tenant(p) for p in pending) if t]
        batch_tenant = (
            max(set(tenants), key=tenants.count) if tenants else None
        )
        bind_ctx = (
            reqctx.bind(reqctx.RequestContext(tenant=batch_tenant))
            if batch_tenant is not None
            else contextlib.nullcontext()
        )
        try:
            with bind_ctx:
                return self.solver.solve(
                    pending,
                    provisioners,
                    instance_types,
                    daemonset_pods=daemonset_pods,
                    state_nodes=state_nodes,
                    kube_client=self.kube_client,
                    cluster=self.cluster,
                )
        except Exception as solve_exc:
            if self.fallback_solver is self.solver:
                raise
            # solver outage -> host greedy fallback (SURVEY.md section 7.8)
            LOG.error(
                "solver raised, using fallback solver",
                error=type(solve_exc).__name__, error_detail=str(solve_exc),
                pods=len(pending),
            )
            return self.fallback_solver.solve(
                pending,
                provisioners,
                instance_types,
                daemonset_pods=daemonset_pods,
                state_nodes=state_nodes,
                kube_client=self.kube_client,
                cluster=self.cluster,
            )

    # -- launching ---------------------------------------------------------

    def launch_machines(
        self, machines: List[SolvedMachine], opts: Optional[LaunchOptions] = None
    ) -> List[str]:
        """Parallel launch (provisioner.go:130-148); failures leave ""."""
        names, _ = self._launch_machines_with_errors(machines, opts)
        return names

    @staticmethod
    def _launch_retryable(err: Exception) -> bool:
        """Failures a re-solve can beat: capacity outages (the offering is
        now ICE-masked) and transient transport faults. Request defects
        (IncompatibleRequirementsError), policy stops (limits exceeded,
        provisioner deleted), and configuration errors (bare OSErrors like
        PermissionError/FileNotFoundError from a vendor SDK) would re-fail
        identically — no retrigger."""
        if isinstance(err, IncompatibleRequirementsError):
            return False
        return isinstance(
            err, (InsufficientCapacityError, ConnectionError, TimeoutError)
        )

    def _schedule_launch_retry(self, failures: int) -> None:
        """Arm the next launch re-trigger deadline: jittered exponential
        from the batch idle window on consecutive failures, capped at 30s."""
        from karpenter_core_tpu.api.settings import current
        from karpenter_core_tpu.utils.backoff import full_jitter

        settings = self.batcher.settings or current()
        base = max(settings.batch_idle_duration, 0.05)
        self._schedule_launch_retry_in(
            max(full_jitter(max(failures - 1, 0), base, cap=30.0), base)
        )

    def _schedule_launch_retry_in(self, delay: float) -> None:
        import time as time_mod

        with self._mu:
            self._launch_retry_at = time_mod.monotonic() + delay

    def _maybe_fire_launch_retry(self) -> None:
        """Fire a due launch re-trigger (called from the reconcile loop
        before the batch wait; step()-mode passes solve unconditionally so
        it never needs the trigger)."""
        import time as time_mod

        with self._mu:
            due_at = self._launch_retry_at
            if due_at is None or time_mod.monotonic() < due_at:
                return
            self._launch_retry_at = None
        self.batcher.trigger()

    def _launch_machines_with_errors(
        self, machines: List[SolvedMachine], opts: Optional[LaunchOptions] = None
    ) -> Tuple[List[str], List[Exception]]:
        """launch_machines + the per-machine exceptions (reconcile uses the
        classification to decide whether a re-solve can make progress)."""
        opts = opts or LaunchOptions()
        if not machines:
            return [], []
        with concurrent.futures.ThreadPoolExecutor(max_workers=max(len(machines), 1)) as pool:
            futures = [pool.submit(self._launch_one, m, opts) for m in machines]
            names: List[str] = []
            errors: List[Exception] = []
            for f in futures:
                try:
                    names.append(f.result())
                except Exception as e:  # noqa: BLE001 — classified below
                    names.append("")
                    errors.append(e)
                    if isinstance(e, InsufficientCapacityError):
                        reason = "insufficient_capacity"
                    elif self._launch_retryable(e):
                        reason = "transient"
                    else:
                        reason = "error"
                    LAUNCH_FAILURES.inc({"reason": reason})
                    LOG.warning(
                        "machine launch failed", reason=reason,
                        error=type(e).__name__, error_detail=str(e),
                    )
        return names, errors

    def _launch_one(self, machine: SolvedMachine, opts: LaunchOptions) -> str:
        """provisioner.go:304-361."""
        latest = self.kube_client.get("Provisioner", "", machine.provisioner_name)
        if latest is None:
            raise RuntimeError(f"provisioner {machine.provisioner_name} not found")
        if latest.spec.limits is not None:
            err = latest.spec.limits.exceeded_by(latest.status.resources)
            if err:
                raise RuntimeError(err)

        from karpenter_core_tpu.scheduling.requirements import Requirements

        template = copy.copy(machine.template)  # templates are shared across machines
        template.instance_type_options = list(machine.instance_type_options)
        template.requirements = Requirements(machine.requirements.values())
        template.requests = dict(machine.requests)
        machine_cr = template.to_machine()
        try:
            # chaos hook: the SPI edge every vendor launch crosses
            chaos.maybe_fail(chaos.CLOUDPROVIDER_CREATE)
            created = self.cloud_provider.create(machine_cr)
        except InsufficientCapacityError as e:
            # remember the exhausted offering so the retrigger's re-solve
            # masks it instead of re-placing pods on the same dead pool
            self.ice_cache.record(e)
            raise

        # persist the launch-intent Machine record for the lifecycle
        # controllers (machine.Controller); named after the created node so
        # node<->machine lookups are 1:1
        machine_cr.metadata.name = created.metadata.name
        machine_cr.status.provider_id = created.status.provider_id
        machine_cr.status.capacity = dict(created.status.capacity)
        machine_cr.status.allocatable = dict(created.status.allocatable)
        machine_cr.metadata.labels.update(created.metadata.labels)
        # providerID/capacity/allocatable live under the status subresource;
        # rebase on apply's returned rv so the status PUT never 409s
        applied = self.kube_client.apply(machine_cr)
        machine_cr.metadata.resource_version = applied.metadata.resource_version
        self.kube_client.update_status(machine_cr)

        # eagerly create the Node (provisioner.go:337-349)
        node = template.to_node()
        node.metadata.name = created.metadata.name
        node.metadata.labels.update(created.metadata.labels)
        node.spec.provider_id = created.status.provider_id
        node.status = NodeStatus()
        try:
            self.kube_client.create(node)
        except Exception:
            pass  # already self-registered (idempotent, provisioner.go:344-349)
        self.cluster.update_node(node)
        self.cluster.nominate_node_for_pod(node.metadata.name)
        if opts.record_pod_nomination and self.recorder:
            for pod in machine.pods:
                self.recorder.nominate_pod(pod, node.metadata.name)
        return node.metadata.name


class PodController:
    """Pod watcher triggering the batcher for provisionable pods
    (provisioning/controller.go:56-75)."""

    def __init__(self, provisioner: ProvisioningController):
        self.provisioner = provisioner

    def reconcile(self, pod: Pod) -> None:
        if not podutils.is_provisionable(pod):
            return
        self.provisioner.trigger()
