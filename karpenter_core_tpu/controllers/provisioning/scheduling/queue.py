"""FFD pod queue with progress detection (reference queue.go:26-110)."""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.kube.objects import Pod
from karpenter_core_tpu.utils import resources


def ffd_sort_key(pod: Pod) -> Tuple:
    """CPU desc, then memory desc, then creation time, then UID
    (queue.go:74-110)."""
    requests = resources.requests_for_pods(pod)
    return (
        -requests.get("cpu", 0.0),
        -requests.get("memory", 0.0),
        pod.metadata.creation_timestamp,
        pod.metadata.uid,
    )


class Queue:
    def __init__(self, pods: List[Pod]):
        self.pods: deque = deque(sorted(pods, key=ffd_sort_key))
        self.last_len: Dict[str, int] = {}

    def pop(self) -> Optional[Pod]:
        """None when empty OR when the head pod returns with an unchanged
        queue length — no progress is being made (queue.go:39-50)."""
        if not self.pods:
            return None
        pod = self.pods[0]
        if self.last_len.get(pod.metadata.uid) == len(self.pods):
            return None
        return self.pods.popleft()

    def push(self, pod: Pod, relaxed: bool) -> None:
        """Re-queue a failed pod; relaxation resets staleness tracking
        (queue.go:53-60)."""
        self.pods.append(pod)
        if relaxed:
            self.last_len = {}
        else:
            self.last_len[pod.metadata.uid] = len(self.pods)

    def list(self) -> List[Pod]:
        return list(self.pods)
