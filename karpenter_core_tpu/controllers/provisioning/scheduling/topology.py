"""Topology tracking: spread / pod-affinity / pod-anti-affinity groups.

Mirrors reference pkg/controllers/provisioning/scheduling/{topology,
topologygroup,topologynodefilter}.go: TopologyGroups are hashed for sharing
across pods; per-domain counts are seeded by listing cluster pods
(countDomains); AddRequirements tightens node requirements to viable domains
(kube-scheduler skew rule for spreads, existing-domain mask for affinity,
zero-count mask for anti-affinity); Record commits a placement.

The TPU path (ops/topology kernels) encodes these same domain-count tensors
on device; this module is the semantic oracle and the host fallback.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from karpenter_core_tpu.kube.objects import (
    LABEL_HOSTNAME,
    LabelSelector,
    Pod,
    PodAffinityTerm,
)
from karpenter_core_tpu.scheduling.requirement import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    Requirement,
)
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.utils import podutils

MAX_SKEW_UNBOUNDED = 2**31 - 1

TOPOLOGY_TYPE_SPREAD = "topology spread"
TOPOLOGY_TYPE_POD_AFFINITY = "pod affinity"
TOPOLOGY_TYPE_POD_ANTI_AFFINITY = "pod anti-affinity"


def _selector_canonical(selector: Optional[LabelSelector]) -> Tuple:
    if selector is None:
        return ("nil",)
    return (
        tuple(sorted(selector.match_labels.items())),
        tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values))) for e in selector.match_expressions
            )
        ),
    )


def _selector_matches(selector: Optional[LabelSelector], labels: Dict[str, str]) -> bool:
    """metav1.LabelSelectorAsSelector semantics: nil matches nothing, empty
    matches everything."""
    if selector is None:
        return False
    return selector.matches(labels)


class TopologyNodeFilter:
    """OR-of-terms node filter for spread constraints
    (topologynodefilter.go:15-56)."""

    def __init__(self, terms: List[Requirements]):
        self.terms = terms

    @classmethod
    def for_pod(cls, pod: Pod) -> "TopologyNodeFilter":
        node_selector_reqs = Requirements.from_labels(pod.spec.node_selector)
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None or not affinity.node_affinity.required:
            return cls([node_selector_reqs])
        terms = []
        for term in affinity.node_affinity.required:
            reqs = Requirements(node_selector_reqs.values())
            reqs.add(*Requirements.from_node_selector_requirements(*term.match_expressions).values())
            terms.append(reqs)
        return cls(terms)

    @classmethod
    def empty(cls) -> "TopologyNodeFilter":
        return cls([])

    def matches_requirements(self, requirements: Requirements) -> bool:
        if not self.terms:
            return True
        return any(requirements.compatible(term) is None for term in self.terms)

    def matches_labels(self, labels: Dict[str, str]) -> bool:
        return self.matches_requirements(Requirements.from_labels(labels))

    def canonical(self) -> Tuple:
        out = []
        for term in self.terms:
            out.append(
                tuple(
                    sorted(
                        (r.key, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than)
                        for r in term.values()
                    )
                )
            )
        return tuple(sorted(out))


class TopologyGroup:
    """topologygroup.go:51-85."""

    def __init__(
        self,
        topology_type: str,
        key: str,
        pod: Optional[Pod],
        namespaces: Set[str],
        selector: Optional[LabelSelector],
        max_skew: int,
        domains: Optional[Set[str]],
    ):
        self.type = topology_type
        self.key = key
        self.namespaces = set(namespaces)
        self.selector = selector
        self.max_skew = max_skew
        self.domains: Dict[str, int] = {d: 0 for d in (domains or set())}
        self.owners: Set[str] = set()  # pod UIDs that carry this rule
        if topology_type == TOPOLOGY_TYPE_SPREAD and pod is not None:
            self.node_filter = TopologyNodeFilter.for_pod(pod)
        else:
            self.node_filter = TopologyNodeFilter.empty()

    # -- next-domain selection (topologygroup.go:82-98,155-243) -----------

    def get(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type == TOPOLOGY_TYPE_SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == TOPOLOGY_TYPE_POD_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains)

    def record(self, *domains: str) -> None:
        for domain in domains:
            self.domains[domain] = self.domains.get(domain, 0) + 1

    def counts(self, pod: Pod, requirements: Requirements) -> bool:
        """Whether the pod's placement under `requirements` counts for this
        group (topologygroup.go:101-103)."""
        return self._selects(pod) and self.node_filter.matches_requirements(requirements)

    def register(self, *domains: str) -> None:
        for domain in domains:
            self.domains.setdefault(domain, 0)

    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    def hash_key(self) -> Tuple:
        """Identity for sharing across pods (topologygroup.go:137-153)."""
        return (
            self.key,
            self.type,
            tuple(sorted(self.namespaces)),
            _selector_canonical(self.selector),
            self.max_skew,
            self.node_filter.canonical(),
        )

    def _next_domain_spread(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """kube-scheduler skew rule: count+self - min <= maxSkew, pick the
        min-count domain (topologygroup.go:155-182)."""
        min_count = self._domain_min_count(pod_domains)
        self_selecting = self._selects(pod)
        best_domain = None
        best_count = MAX_SKEW_UNBOUNDED
        for domain in sorted(self.domains):
            if node_domains.has(domain):
                count = self.domains[domain]
                if self_selecting:
                    count += 1
                if count - min_count <= self.max_skew and count < best_count:
                    best_domain = domain
                    best_count = count
        if best_domain is None:
            return Requirement(pod_domains.key, OP_DOES_NOT_EXIST)
        return Requirement(pod_domains.key, OP_IN, [best_domain])

    def _domain_min_count(self, domains: Requirement) -> int:
        """Global min over domains the pod can select; hostname always 0
        (topologygroup.go:185-199)."""
        if self.key == LABEL_HOSTNAME:
            return 0
        counts = [c for d, c in self.domains.items() if domains.has(d)]
        return min(counts) if counts else MAX_SKEW_UNBOUNDED

    def _next_domain_affinity(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """topologygroup.go:202-233: domains with matching pods; a
        self-selecting pod may seed the first viable domain."""
        options = Requirement(pod_domains.key, OP_DOES_NOT_EXIST)
        for domain in sorted(self.domains):
            if pod_domains.has(domain) and self.domains[domain] > 0:
                options.insert(domain)
        if options.len() == 0 and self._selects(pod):
            intersected = pod_domains.intersection(node_domains)
            for domain in sorted(self.domains):
                if intersected.has(domain):
                    options.insert(domain)
                    break
            for domain in sorted(self.domains):
                if pod_domains.has(domain):
                    options.insert(domain)
                    break
        return options

    def _next_domain_anti_affinity(self, domains: Requirement) -> Requirement:
        """Only zero-count domains remain viable (topologygroup.go:235-243)."""
        options = Requirement(domains.key, OP_DOES_NOT_EXIST)
        for domain in sorted(self.domains):
            if domains.has(domain) and self.domains[domain] == 0:
                options.insert(domain)
        return options

    def _selects(self, pod: Pod) -> bool:
        return pod.metadata.namespace in self.namespaces and _selector_matches(
            self.selector, pod.metadata.labels
        )


class Topology:
    """topology.go:37-80."""

    def __init__(
        self,
        kube_client,
        cluster,
        domains: Dict[str, Set[str]],
        pods: List[Pod],
        update_pods: Optional[List[Pod]] = None,
    ):
        """update_pods: subset of `pods` to register groups/ownership for —
        the tensor encoder passes one representative per pod-spec equivalence
        class (group membership is a pure function of spec+labels+namespace),
        while the host scheduler registers every pod. `pods` always defines
        the excluded set (topology.go:56-58)."""
        self.kube_client = kube_client
        self.cluster = cluster
        self.domains = domains
        self.topologies: Dict[Tuple, TopologyGroup] = {}
        self.inverse_topologies: Dict[Tuple, TopologyGroup] = {}
        # pods in the current batch are excluded from domain counting: their
        # placement is decided by this solve (topology.go:56-58)
        self.excluded_pods: Set[str] = {p.metadata.uid for p in pods}
        self._update_inverse_affinities()
        for pod in pods if update_pods is None else update_pods:
            self.update(pod)

    # -- batch maintenance ------------------------------------------------

    def update(self, pod: Pod) -> None:
        """(Re-)derive the pod's topology groups after relaxation
        (topology.go:86-117)."""
        for tg in self.topologies.values():
            tg.remove_owner(pod.metadata.uid)

        if podutils.has_pod_anti_affinity(pod):
            self._update_inverse_anti_affinity(pod, None)

        for tg in self._new_for_topologies(pod) + self._new_for_affinities(pod):
            key = tg.hash_key()
            existing = self.topologies.get(key)
            if existing is None:
                self._count_domains(tg)
                self.topologies[key] = tg
            else:
                tg = existing
            tg.add_owner(pod.metadata.uid)

    def record(self, pod: Pod, requirements: Requirements) -> None:
        """Commit a placement into domain counts (topology.go:120-143)."""
        for tg in self.topologies.values():
            if tg.counts(pod, requirements):
                domains = requirements.get_requirement(tg.key)
                if tg.type == TOPOLOGY_TYPE_POD_ANTI_AFFINITY:
                    tg.record(*domains.values_list())
                elif domains.len() == 1:
                    tg.record(domains.values_list()[0])
        for tg in self.inverse_topologies.values():
            if tg.is_owned_by(pod.metadata.uid):
                tg.record(*requirements.get_requirement(tg.key).values_list())

    def add_requirements(
        self, pod_requirements: Requirements, node_requirements: Requirements, pod: Pod
    ) -> Tuple[Optional[Requirements], Optional[str]]:
        """Tighten node requirements to viable domains (topology.go:149-167).
        Returns (requirements, error)."""
        requirements = Requirements(node_requirements.values())
        for tg in self._get_matching_topologies(pod, node_requirements):
            pod_domains = pod_requirements.get_requirement(tg.key)
            node_domains = node_requirements.get_requirement(tg.key)
            domains = tg.get(pod, pod_domains, node_domains)
            if domains.len() == 0:
                return None, (
                    f"unsatisfiable topology constraint for {tg.type}, key={tg.key} "
                    f"(counts = {tg.domains}, podDomains = {pod_domains!r}, "
                    f"nodeDomains = {node_domains!r})"
                )
            requirements.add(domains)
        return requirements, None

    def register(self, topology_key: str, domain: str) -> None:
        """Register a new domain (e.g. a hostname) (topology.go:170-180)."""
        for tg in self.topologies.values():
            if tg.key == topology_key:
                tg.register(domain)
        for tg in self.inverse_topologies.values():
            if tg.key == topology_key:
                tg.register(domain)

    # -- construction helpers ---------------------------------------------

    def _update_inverse_affinities(self) -> None:
        """Seed inverse anti-affinity from pods already in the cluster
        (topology.go:183-196)."""
        if self.cluster is None:
            return

        def visit(pod: Pod, node) -> bool:
            if pod.metadata.uid not in self.excluded_pods:
                self._update_inverse_anti_affinity(pod, node.metadata.labels)
            return True

        self.cluster.for_pods_with_anti_affinity(visit)

    def _update_inverse_anti_affinity(self, pod: Pod, node_labels: Optional[Dict[str, str]]) -> None:
        """topology.go:200-227: an inverse group tracks where a pod with
        anti-affinity LANDED so future matching pods avoid those domains."""
        for term in pod.spec.affinity.pod_anti_affinity.required:
            namespaces = self._build_namespace_list(
                pod.metadata.namespace, term.namespaces, term.namespace_selector
            )
            tg = TopologyGroup(
                TOPOLOGY_TYPE_POD_ANTI_AFFINITY,
                term.topology_key,
                pod,
                namespaces,
                term.label_selector,
                MAX_SKEW_UNBOUNDED,
                self.domains.get(term.topology_key, set()),
            )
            key = tg.hash_key()
            existing = self.inverse_topologies.get(key)
            if existing is None:
                self.inverse_topologies[key] = tg
            else:
                tg = existing
            if node_labels and tg.key in node_labels:
                tg.record(node_labels[tg.key])
            tg.add_owner(pod.metadata.uid)

    def _count_domains(self, tg: TopologyGroup) -> None:
        """Seed domain counts from scheduled cluster pods (topology.go:231-276)."""
        if self.kube_client is None:
            return
        pods: List[Pod] = []
        for ns in tg.namespaces:
            pods.extend(self.kube_client.list("Pod", namespace=ns, selector=tg.selector))
        for pod in pods:
            if not podutils.is_scheduled(pod) or podutils.is_terminal(pod) or podutils.is_terminating(pod):
                continue
            if pod.metadata.uid in self.excluded_pods:
                continue
            node = self.kube_client.get("Node", "", pod.spec.node_name)
            if node is None:
                continue
            domain = node.metadata.labels.get(tg.key)
            if domain is None and tg.key == LABEL_HOSTNAME:
                domain = node.metadata.name
            if domain is None:
                continue  # node without the domain label doesn't count
            if not tg.node_filter.matches_labels(node.metadata.labels):
                continue
            tg.record(domain)

    def _new_for_topologies(self, pod: Pod) -> List[TopologyGroup]:
        return [
            TopologyGroup(
                TOPOLOGY_TYPE_SPREAD,
                cs.topology_key,
                pod,
                {pod.metadata.namespace},
                cs.label_selector,
                cs.max_skew,
                self.domains.get(cs.topology_key, set()),
            )
            for cs in pod.spec.topology_spread_constraints
        ]

    def _new_for_affinities(self, pod: Pod) -> List[TopologyGroup]:
        """Both hard and soft affinity terms become groups (topology.go:283-322)."""
        groups: List[TopologyGroup] = []
        affinity = pod.spec.affinity
        if affinity is None:
            return groups
        terms: List[Tuple[str, PodAffinityTerm]] = []
        if affinity.pod_affinity is not None:
            terms += [(TOPOLOGY_TYPE_POD_AFFINITY, t) for t in affinity.pod_affinity.required]
            terms += [
                (TOPOLOGY_TYPE_POD_AFFINITY, t.pod_affinity_term)
                for t in affinity.pod_affinity.preferred
            ]
        if affinity.pod_anti_affinity is not None:
            terms += [
                (TOPOLOGY_TYPE_POD_ANTI_AFFINITY, t) for t in affinity.pod_anti_affinity.required
            ]
            terms += [
                (TOPOLOGY_TYPE_POD_ANTI_AFFINITY, t.pod_affinity_term)
                for t in affinity.pod_anti_affinity.preferred
            ]
        for topology_type, term in terms:
            namespaces = self._build_namespace_list(
                pod.metadata.namespace, term.namespaces, term.namespace_selector
            )
            groups.append(
                TopologyGroup(
                    topology_type,
                    term.topology_key,
                    pod,
                    namespaces,
                    term.label_selector,
                    MAX_SKEW_UNBOUNDED,
                    self.domains.get(term.topology_key, set()),
                )
            )
        return groups

    def _build_namespace_list(
        self, namespace: str, namespaces: List[str], selector: Optional[LabelSelector]
    ) -> Set[str]:
        """topology.go:327-347."""
        if not namespaces and selector is None:
            return {namespace}
        if selector is None:
            return set(namespaces)
        selected = set(namespaces)
        if self.kube_client is not None:
            for ns in self.kube_client.list("Namespace", selector=selector):
                selected.add(ns.metadata.name)
        return selected

    def _get_matching_topologies(
        self, pod: Pod, requirements: Requirements
    ) -> List[TopologyGroup]:
        """Groups that control p's scheduling, plus inverse groups p counts
        against (topology.go:351-364)."""
        matching = [
            tg for tg in self.topologies.values() if tg.is_owned_by(pod.metadata.uid)
        ]
        matching += [
            tg for tg in self.inverse_topologies.values() if tg.counts(pod, requirements)
        ]
        return matching
