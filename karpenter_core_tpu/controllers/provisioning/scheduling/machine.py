"""In-flight scheduling machines: the constraint accumulators of the solve.

Mirrors reference pkg/controllers/provisioning/scheduling/{machine,
existingnode,machinetemplate}.go. A SchedulingMachine accumulates pods and
monotonically narrows its InstanceTypeOptions through the
compatible ∧ fits ∧ hasOffering filter (machine.go:137-159) — exactly the
feasibility expression the TPU kernel (ops/feasibility.py) evaluates densely.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.kube.objects import (
    LABEL_HOSTNAME,
    LABEL_TOPOLOGY_ZONE,
    Pod,
    ResourceList,
)
from karpenter_core_tpu.scheduling import taints as taints_mod
from karpenter_core_tpu.scheduling.hostportusage import HostPortUsage
# MachineTemplate lives in the neutral scheduling layer (the solver encodes
# it too); re-exported here for compatibility with existing imports.
from karpenter_core_tpu.scheduling.machinetemplate import (  # noqa: F401
    MachineTemplate,
    next_node_id,
)
from karpenter_core_tpu.scheduling.requirement import OP_IN, Requirement
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.utils import resources as resources_util


class SchedulingMachine:
    """A node being provisioned by this solve (machine.go:31-115)."""

    def __init__(
        self,
        template: MachineTemplate,
        topology,
        daemon_resources: ResourceList,
        instance_types: List[InstanceType],
    ):
        hostname = f"hostname-placeholder-{next_node_id():04d}"
        topology.register(LABEL_HOSTNAME, hostname)
        self.template = template
        self.provisioner_name = template.provisioner_name
        self.labels = template.labels
        self.annotations = template.annotations
        self.taints = template.taints
        self.startup_taints = template.startup_taints
        self.kubelet = template.kubelet
        self.provider = template.provider
        self.provider_ref = template.provider_ref
        self.requirements = Requirements(template.requirements.values())
        self.requirements.add(Requirement(LABEL_HOSTNAME, OP_IN, [hostname]))
        self.instance_type_options = list(instance_types)
        self.requests: ResourceList = dict(daemon_resources)
        self.pods: List[Pod] = []
        self.topology = topology
        self.hostport_usage = HostPortUsage()

    def add(self, pod: Pod) -> Optional[str]:
        """Try to commit the pod; returns an error string or None
        (machine.go:62-107)."""
        err = taints_mod.tolerates(self.taints, pod)
        if err:
            return err
        err = self.hostport_usage.validate(pod)
        if err:
            return err

        machine_requirements = Requirements(self.requirements.values())
        pod_requirements = Requirements.from_pod(pod)
        err = machine_requirements.compatible(pod_requirements)
        if err:
            return f"incompatible requirements, {err}"
        machine_requirements.add(*pod_requirements.values())

        topology_requirements, err = self.topology.add_requirements(
            pod_requirements, machine_requirements, pod
        )
        if err:
            return err
        err = machine_requirements.compatible(topology_requirements)
        if err:
            return err
        machine_requirements.add(*topology_requirements.values())

        requests = resources_util.merge(self.requests, resources_util.requests_for_pods(pod))
        instance_types = filter_instance_types_by_requirements(
            self.instance_type_options, machine_requirements, requests
        )
        if not instance_types:
            return (
                f"no instance type satisfied resources "
                f"{resources_util.to_string(resources_util.requests_for_pods(pod))} "
                f"and requirements {machine_requirements!r}"
            )

        self.pods.append(pod)
        self.instance_type_options = instance_types
        self.requests = requests
        self.requirements = machine_requirements
        self.topology.record(pod, machine_requirements)
        self.hostport_usage.add(pod)
        return None

    def finalize_scheduling(self) -> None:
        """Drop the placeholder hostname requirement (machine.go:109-115)."""
        self.requirements.pop(LABEL_HOSTNAME, None)

    def to_machine_template(self) -> MachineTemplate:
        """Fold accumulated state back into a launchable template."""
        out = MachineTemplate.__new__(MachineTemplate)
        out.provisioner_name = self.provisioner_name
        out.provider = self.provider
        out.provider_ref = self.provider_ref
        out.kubelet = self.kubelet
        out.annotations = dict(self.annotations)
        out.labels = dict(self.labels)
        out.taints = list(self.taints)
        out.startup_taints = list(self.startup_taints)
        out.requirements = self.requirements
        out.requests = dict(self.requests)
        out.instance_type_options = list(self.instance_type_options)
        return out

    def __repr__(self) -> str:
        names = ", ".join(it.name for it in self.instance_type_options[:5])
        extra = len(self.instance_type_options) - 5
        if extra > 0:
            names += f" and {extra} other(s)"
        return (
            f"machine with {len(self.pods)} pods requesting "
            f"{resources_util.to_string(self.requests)} from types {names}"
        )


class ExistingNode:
    """A real or in-flight node considered by the solve
    (existingnode.go:28-115)."""

    def __init__(self, state_node, topology, daemon_resources: ResourceList):
        remaining_daemon = resources_util.subtract(
            daemon_resources, state_node.total_daemonset_requests()
        )
        remaining_daemon = {k: max(v, 0.0) for k, v in remaining_daemon.items()}
        self.state_node = state_node
        self.pods: List[Pod] = []
        self.topology = topology
        self.requests: ResourceList = remaining_daemon
        self.requirements = Requirements.from_labels(state_node.labels())
        self.requirements.add(Requirement(LABEL_HOSTNAME, OP_IN, [state_node.hostname()]))
        topology.register(LABEL_HOSTNAME, state_node.hostname())

    def name(self) -> str:
        return self.state_node.name()

    def add(self, pod: Pod) -> Optional[str]:
        """existingnode.go:62-115."""
        err = taints_mod.tolerates(self.state_node.taints(), pod)
        if err:
            return err
        err = self.state_node.hostport_usage.validate(pod)
        if err:
            return err
        mounted = self.state_node.volume_usage.validate(pod)
        if mounted.exceeds(self.state_node.volume_limits):
            return "would exceed node volume limits"

        requests = resources_util.merge(self.requests, resources_util.requests_for_pods(pod))
        if not resources_util.fits(requests, self.state_node.available()):
            return "exceeds node resources"

        node_requirements = Requirements(self.requirements.values())
        pod_requirements = Requirements.from_pod(pod)
        err = node_requirements.compatible(pod_requirements)
        if err:
            return err
        node_requirements.add(*pod_requirements.values())

        topology_requirements, err = self.topology.add_requirements(
            pod_requirements, node_requirements, pod
        )
        if err:
            return err
        err = node_requirements.compatible(topology_requirements)
        if err:
            return err
        node_requirements.add(*topology_requirements.values())

        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_requirements
        self.topology.record(pod, node_requirements)
        self.state_node.hostport_usage.add(pod)
        self.state_node.volume_usage.add(pod)
        return None


def filter_instance_types_by_requirements(
    instance_types: List[InstanceType],
    requirements: Requirements,
    requests: ResourceList,
) -> List[InstanceType]:
    """compatible ∧ fits ∧ hasOffering (machine.go:137-159) — the expression
    the TPU feasibility kernel lowers to tensor masks."""
    return [
        it
        for it in instance_types
        if _compatible(it, requirements)
        and _fits(it, requests)
        and _has_offering(it, requirements)
    ]


def _compatible(instance_type: InstanceType, requirements: Requirements) -> bool:
    return instance_type.requirements.intersects(requirements) is None


def _fits(instance_type: InstanceType, requests: ResourceList) -> bool:
    return resources_util.fits(requests, instance_type.allocatable())


def _has_offering(instance_type: InstanceType, requirements: Requirements) -> bool:
    for offering in instance_type.offerings.available():
        if (
            LABEL_TOPOLOGY_ZONE not in requirements
            or requirements.get_requirement(LABEL_TOPOLOGY_ZONE).has(offering.zone)
        ) and (
            api_labels.LABEL_CAPACITY_TYPE not in requirements
            or requirements.get_requirement(api_labels.LABEL_CAPACITY_TYPE).has(
                offering.capacity_type
            )
        ):
            return True
    return False
