"""Compatibility shim: Preferences moved to the neutral scheduling layer
(karpenter_core_tpu/scheduling/preferences.py) so the solver's relaxation
loop can use it without a solver -> controllers layering edge."""
from karpenter_core_tpu.scheduling.preferences import Preferences  # noqa: F401
