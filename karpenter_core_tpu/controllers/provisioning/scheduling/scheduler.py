"""Scheduler — greedy first-fit solve with progress queue and relaxation.

Mirrors reference pkg/controllers/provisioning/scheduling/scheduler.go:42-312.
This is the HOST path: the in-process fallback solver and the differential
oracle for the TPU tensor solver (solver/ + ops/). The TPU path replaces
Solve()'s per-pod loop with dense pod×type feasibility + packing kernels; this
implementation defines the semantics those kernels must reproduce.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.controllers.provisioning.scheduling.machine import (
    ExistingNode,
    MachineTemplate,
    SchedulingMachine,
    filter_instance_types_by_requirements,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.preferences import Preferences
from karpenter_core_tpu.controllers.provisioning.scheduling.queue import Queue
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import Topology
from karpenter_core_tpu.kube.objects import Pod, ResourceList
from karpenter_core_tpu.obs import TRACER
from karpenter_core_tpu.scheduling import taints as taints_mod
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class SchedulerOptions:
    simulation_mode: bool = False


@dataclass
class SchedulingResult:
    new_machines: List[SchedulingMachine] = field(default_factory=list)
    existing_nodes: List[ExistingNode] = field(default_factory=list)
    errors: Dict[str, str] = field(default_factory=dict)  # pod uid -> last error
    failed_pods: List[Pod] = field(default_factory=list)

    def pod_count_new(self) -> int:
        return sum(len(m.pods) for m in self.new_machines)

    def pod_count_existing(self) -> int:
        return sum(len(n.pods) for n in self.existing_nodes)


class Scheduler:
    """scheduler.go:79-133."""

    def __init__(
        self,
        kube_client,
        machine_templates: List[MachineTemplate],
        provisioners: List[Provisioner],
        cluster,
        state_nodes: List,
        topology: Topology,
        instance_types: Dict[str, List[InstanceType]],
        daemonset_pods: List[Pod],
        recorder=None,
        opts: Optional[SchedulerOptions] = None,
    ):
        # provisioner PreferNoSchedule taints enable the extra relaxation
        # (scheduler.go:48-56)
        tolerate_prefer_no_schedule = any(
            taint.effect == "PreferNoSchedule"
            for prov in provisioners
            for taint in prov.spec.taints
        )
        self.kube_client = kube_client
        self.machine_templates = machine_templates
        self.topology = topology
        self.cluster = cluster
        self.instance_types = instance_types
        self.daemon_overhead = _get_daemon_overhead(machine_templates, daemonset_pods)
        self.recorder = recorder
        self.opts = opts or SchedulerOptions()
        self.preferences = Preferences(tolerate_prefer_no_schedule)
        self.remaining_resources: Dict[str, ResourceList] = {
            p.name: dict(p.spec.limits.resources)
            for p in provisioners
            if p.spec.limits is not None
        }
        self.new_machines: List[SchedulingMachine] = []
        self.existing_nodes: List[ExistingNode] = []
        self._calculate_existing_machines(state_nodes, daemonset_pods)

    def solve(self, pods: List[Pod]) -> SchedulingResult:
        """The hot loop (scheduler.go:96-133): pop pod → try existing nodes →
        try open machines (ascending pod count) → open machine from the first
        compatible weighted template; on failure relax and re-push."""
        with TRACER.span("scheduler.solve", pods=len(pods)) as sp:
            result = self._solve_traced(pods)
            sp.set(
                machines=len(result.new_machines),
                failed=len(result.failed_pods),
            )
            return result

    def _solve_traced(self, pods: List[Pod]) -> SchedulingResult:
        errors: Dict[str, str] = {}
        q = Queue(pods)
        while True:
            pod = q.pop()
            if pod is None:
                break
            err = self._add(pod)
            if err is None:
                errors.pop(pod.metadata.uid, None)
                continue
            errors[pod.metadata.uid] = err
            relaxed = self.preferences.relax(pod)
            q.push(pod, relaxed)
            if relaxed:
                self.topology.update(pod)

        for machine in self.new_machines:
            machine.finalize_scheduling()

        failed = q.list()
        result = SchedulingResult(
            new_machines=self.new_machines,
            existing_nodes=self.existing_nodes,
            errors={p.metadata.uid: errors.get(p.metadata.uid, "") for p in failed},
            failed_pods=failed,
        )
        if not self.opts.simulation_mode:
            self._record_results(result)
        return result

    # -- internals ---------------------------------------------------------

    def _add(self, pod: Pod) -> Optional[str]:
        """scheduler.go:177-222."""
        for node in self.existing_nodes:
            if node.add(pod) is None:
                return None

        # pick the open machine with fewest pods first (scheduler.go:186-193)
        self.new_machines.sort(key=lambda m: len(m.pods))
        for machine in self.new_machines:
            if machine.add(pod) is None:
                return None

        errs: List[str] = []
        for template in self.machine_templates:
            instance_types = self.instance_types.get(template.provisioner_name, [])
            remaining = self.remaining_resources.get(template.provisioner_name)
            if remaining is not None:
                instance_types = filter_by_remaining_resources(instance_types, remaining)
                if not instance_types:
                    errs.append(
                        f"all available instance types exceed limits for provisioner "
                        f'"{template.provisioner_name}"'
                    )
                    continue
            machine = SchedulingMachine(
                template,
                self.topology,
                self.daemon_overhead.get(id(template), {}),
                instance_types,
            )
            err = machine.add(pod)
            if err is not None:
                errs.append(f'incompatible with provisioner "{template.provisioner_name}", {err}')
                continue
            self.new_machines.append(machine)
            if remaining is not None:
                # pessimistic max-capacity subtraction (scheduler.go:276-293)
                self.remaining_resources[template.provisioner_name] = subtract_max(
                    remaining, machine.instance_type_options
                )
            return None
        return "; ".join(errs) if errs else "no machine templates configured"

    def _calculate_existing_machines(self, state_nodes: List, daemonset_pods: List[Pod]) -> None:
        """scheduler.go:224-251."""
        for state_node in state_nodes:
            if not state_node.owned():
                continue
            daemons = [
                p
                for p in daemonset_pods
                if taints_mod.tolerates(state_node.taints(), p) is None
                and Requirements.from_labels(state_node.labels()).compatible(
                    Requirements.from_pod(p)
                )
                is None
            ]
            # PVC -> driver resolution goes through the kube client
            # (volumeusage.go:133-200); a state node built outside the
            # cluster cache may not carry one yet
            if state_node.volume_usage.kube_client is None:
                state_node.volume_usage.kube_client = self.kube_client
            self.existing_nodes.append(
                ExistingNode(
                    state_node,
                    self.topology,
                    resources_util.requests_for_pods(*daemons) if daemons else {"pods": 0.0},
                )
            )
            provisioner_name = state_node.labels().get(api_labels.PROVISIONER_NAME_LABEL_KEY, "")
            if provisioner_name in self.remaining_resources:
                self.remaining_resources[provisioner_name] = resources_util.subtract(
                    self.remaining_resources[provisioner_name], state_node.capacity()
                )

    def _record_results(self, result: SchedulingResult) -> None:
        """scheduler.go:135-175 — nomination + failure events."""
        if self.recorder is None:
            return
        for pod in result.failed_pods:
            self.recorder.pod_failed_to_schedule(pod, result.errors.get(pod.metadata.uid, ""))
        for node in self.existing_nodes:
            if node.pods and self.cluster is not None:
                self.cluster.nominate_node_for_pod(node.name())
            for pod in node.pods:
                self.recorder.nominate_pod(pod, node.name())


def build_scheduler(
    kube_client,
    cluster,
    provisioners: List[Provisioner],
    instance_types: Dict[str, List[InstanceType]],
    pods: List[Pod],
    state_nodes: Optional[List] = None,
    daemonset_pods: Optional[List[Pod]] = None,
    opts: Optional[SchedulerOptions] = None,
    recorder=None,
) -> "Scheduler":
    """Wire a Scheduler the way the Provisioner does (provisioner.go:198-264):
    templates ordered by weight, topology-domain universe from provisioner ∩
    instance-type requirements, topology seeded with the batch pods."""
    from karpenter_core_tpu.api.provisioner import order_by_weight

    provisioners = [
        p for p in order_by_weight(provisioners) if p.metadata.deletion_timestamp is None
    ]
    templates = [MachineTemplate(p) for p in provisioners]
    # CSI attach limits: snapshots that bypassed the cluster informer
    # (direct API use, tests) resolve them from the CSINode objects here —
    # only for owned nodes, the ones the Scheduler will actually pack
    from karpenter_core_tpu.state.node import resolve_volume_limits

    resolve_volume_limits(
        [n for n in (state_nodes or []) if n.owned()], kube_client
    )
    domains = build_domains(provisioners, instance_types)
    topology = Topology(kube_client, cluster, domains, pods)
    return Scheduler(
        kube_client,
        templates,
        provisioners,
        cluster,
        state_nodes or [],
        topology,
        instance_types,
        daemonset_pods or [],
        recorder=recorder,
        opts=opts,
    )


def build_domains(
    provisioners: List[Provisioner], instance_types: Dict[str, List[InstanceType]]
) -> Dict[str, set]:
    """Topology-domain universe: provisioner ∩ instance-type requirement
    values per key (provisioner.go:227-243)."""
    domains: Dict[str, set] = {}
    for provisioner in provisioners:
        prov_reqs = Requirements.from_node_selector_requirements(*provisioner.spec.requirements)
        for instance_type in instance_types.get(provisioner.name, []):
            # intersect so instance-type zones don't expand past the
            # provisioner's own universe (provisioner.go:227-237)
            requirements = Requirements(prov_reqs.values())
            requirements.add(*instance_type.requirements.values())
            for key, requirement in requirements.items():
                domains.setdefault(key, set()).update(requirement.values_list())
        for key, requirement in prov_reqs.items():
            if requirement.operator() == "In":
                domains.setdefault(key, set()).update(requirement.values_list())
    return domains


def _get_daemon_overhead(
    templates: List[MachineTemplate], daemonset_pods: List[Pod]
) -> Dict[int, ResourceList]:
    """Per-template daemon resource overhead (scheduler.go:253-270)."""
    overhead: Dict[int, ResourceList] = {}
    for template in templates:
        daemons = [
            p
            for p in daemonset_pods
            if taints_mod.tolerates(template.taints, p) is None
            and template.requirements.compatible(Requirements.from_pod(p)) is None
        ]
        overhead[id(template)] = (
            resources_util.requests_for_pods(*daemons) if daemons else {"pods": 0.0}
        )
    return overhead


def subtract_max(remaining: ResourceList, instance_types: List[InstanceType]) -> ResourceList:
    """Pessimistically subtract the max capacity over the machine's remaining
    instance-type options (scheduler.go:276-293)."""
    if not instance_types:
        return remaining
    max_caps = resources_util.max_resources(*[it.capacity for it in instance_types])
    return {k: v - max_caps.get(k, 0.0) for k, v in remaining.items()}


def filter_by_remaining_resources(
    instance_types: List[InstanceType], remaining: ResourceList
) -> List[InstanceType]:
    """Exclude types whose capacity would breach provisioner limits
    (scheduler.go:296-312)."""
    out = []
    for it in instance_types:
        if all(it.capacity.get(name, 0.0) <= quantity for name, quantity in remaining.items()):
            out.append(it)
    return out
