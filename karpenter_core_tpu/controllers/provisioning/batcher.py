"""Batching window over pod triggers (reference batcher.go:40-74):
wait for the first trigger, then extend while triggers keep arriving within
the idle window, capped at the max window."""
from __future__ import annotations

import threading
import time

from karpenter_core_tpu.api.settings import Settings, current
from karpenter_core_tpu.obs import TRACER


class Batcher:
    def __init__(self, settings: Settings = None, clock=time.monotonic):
        self.settings = settings
        self.clock = clock
        self._trigger = threading.Event()
        self._mu = threading.Lock()
        self._triggers = 0  # total triggers ever (locked: concurrent pods)
        self._consumed = 0  # triggers attributed to already-closed windows

    def trigger(self) -> None:
        with self._mu:
            self._triggers += 1
        self._trigger.set()

    def wait(self, timeout: float = None, poll: float = 0.01) -> bool:
        """Returns True when a batch window closed with work to do
        (batcher.go:50-74)."""
        settings = self.settings or current()
        if not self._trigger.wait(timeout=timeout):
            return False
        # the span covers the WINDOW (first trigger -> close), not the idle
        # wait above it: the window is the batching latency a pod pays
        # before its solve starts
        start_ns = time.perf_counter_ns()
        start = self.clock()
        last = self.clock()
        self._trigger.clear()
        while True:
            now = self.clock()
            closed = (
                "max" if now - start >= settings.batch_max_duration
                else "idle" if now - last >= settings.batch_idle_duration
                else None
            )
            if closed:
                # everything not yet attributed to a prior window — including
                # triggers that accumulated while wait() was blocked
                with self._mu:
                    folded = self._triggers - self._consumed
                    self._consumed = self._triggers
                TRACER.add_span(
                    "batcher.window", start_ns, time.perf_counter_ns(),
                    closed_by=closed, triggers=folded,
                )
                return True
            # the trigger wait is CAPPED at the time remaining to the
            # nearer of the two close bounds (floored at 0 so a fake
            # clock that jumped past a deadline still re-checks
            # immediately): a nonstop trigger stream returns from the
            # wait instantly over and over, and an uncapped poll quantum
            # both overshot the max bound by up to `poll` per window and
            # burned a busy-spin between triggers. The max deadline is a
            # hard cap — continuous triggers extend `last`, never `start`.
            remaining = min(
                settings.batch_max_duration - (now - start),
                settings.batch_idle_duration - (now - last),
            )
            if self._trigger.wait(timeout=max(min(poll, remaining), 0.0)):
                self._trigger.clear()
                last = self.clock()
