"""Batching window over pod triggers (reference batcher.go:40-74):
wait for the first trigger, then extend while triggers keep arriving within
the idle window, capped at the max window."""
from __future__ import annotations

import threading
import time

from karpenter_core_tpu.api.settings import Settings, current


class Batcher:
    def __init__(self, settings: Settings = None, clock=time.monotonic):
        self.settings = settings
        self.clock = clock
        self._trigger = threading.Event()

    def trigger(self) -> None:
        self._trigger.set()

    def wait(self, timeout: float = None, poll: float = 0.01) -> bool:
        """Returns True when a batch window closed with work to do
        (batcher.go:50-74)."""
        settings = self.settings or current()
        if not self._trigger.wait(timeout=timeout):
            return False
        start = self.clock()
        last = self.clock()
        self._trigger.clear()
        while True:
            now = self.clock()
            if now - start >= settings.batch_max_duration:
                return True
            if now - last >= settings.batch_idle_duration:
                return True
            if self._trigger.wait(timeout=poll):
                self._trigger.clear()
                last = self.clock()
