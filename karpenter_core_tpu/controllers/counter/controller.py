"""Provisioner resource counter.

Mirrors reference pkg/controllers/counter/controller.go:62-93: aggregate per-
provisioner status.resources from cluster state, skipping nodes marked for
deletion.
"""
from __future__ import annotations

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.utils import resources as resources_util


class CounterController:
    def __init__(self, kube_client, cluster):
        self.kube_client = kube_client
        self.cluster = cluster

    def reconcile(self, provisioner: Provisioner) -> None:
        resources = {}
        for node in self.cluster.nodes():
            if node.is_marked_for_deletion():
                continue
            if node.labels().get(api_labels.PROVISIONER_NAME_LABEL_KEY) != provisioner.name:
                continue
            resources = resources_util.merge(resources, node.capacity())
        provisioner.status.resources = resources
        # status subresource write (counter/controller.go:67 Status().Patch):
        # a plain PUT would be silently dropped by the apiserver
        from karpenter_core_tpu.kube.client import NotFoundError

        try:
            self.kube_client.update_status(provisioner)
        except NotFoundError:
            pass  # provisioner deleted mid-reconcile
