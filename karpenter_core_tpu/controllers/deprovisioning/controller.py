"""Deprovisioning controller — the ordered deprovisioner chain.

Mirrors reference pkg/controllers/deprovisioning/controller.go:72-253:
Expiration -> Drift -> Emptiness -> EmptyNodeConsolidation ->
MultiNodeConsolidation -> SingleNodeConsolidation; executes one command per
loop (replace launches first, then cordon+delete+wait); 10s poll.
"""
from __future__ import annotations

import time
from typing import List, Optional

from karpenter_core_tpu.controllers.deprovisioning.consolidation import (
    EmptyNodeConsolidation,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_core_tpu.controllers.deprovisioning.core import (
    ACTION_DO_NOTHING,
    ACTION_REPLACE,
    ACTION_RETRY,
    Command,
    candidate_nodes,
)
from karpenter_core_tpu.controllers.deprovisioning.deprovisioners import (
    Drift,
    Emptiness,
    Expiration,
)
from karpenter_core_tpu.metrics.registry import NAMESPACE, NODES_CREATED, NODES_TERMINATED, REGISTRY
from karpenter_core_tpu.obs.log import get_logger

LOG = get_logger("karpenter.deprovisioning")

POLLING_PERIOD = 10.0  # controller.go:58
MAX_READINESS_WAIT = 9.5 * 60.0  # controller.go:62-70


class DeprovisioningController:
    """controller.go:72-141."""

    def __init__(self, kube_client, cluster, provisioning, cloud_provider, recorder,
                 clock=time.time, validation_ttl: float = 15.0,
                 readiness_poll: float = 1.0, readiness_wait: float = MAX_READINESS_WAIT):
        self.kube_client = kube_client
        self.cluster = cluster
        self.provisioning = provisioning
        self.cloud_provider = cloud_provider
        self.recorder = recorder
        self.clock = clock
        self.readiness_poll = readiness_poll
        self.readiness_wait = readiness_wait
        args = (kube_client, cluster, provisioning, cloud_provider, recorder)
        kwargs = dict(clock=clock, validation_ttl=validation_ttl)
        self.deprovisioners = [
            Expiration(*args, **kwargs),
            Drift(*args, **kwargs),
            Emptiness(*args, **kwargs),
            EmptyNodeConsolidation(*args, **kwargs),
            MultiNodeConsolidation(*args, **kwargs),
            SingleNodeConsolidation(*args, **kwargs),
        ]
        self.actions = REGISTRY.counter(f"{NAMESPACE}_deprovisioning_actions_performed")

    def reconcile(self) -> bool:
        """One pass over the chain; True if a command executed
        (controller.go:107-141)."""
        for deprovisioner in self.deprovisioners:
            candidates = candidate_nodes(
                self.cluster,
                self.kube_client,
                self.cloud_provider,
                deprovisioner.should_deprovision,
                self.clock,
            )
            if not candidates:
                continue
            cmd = deprovisioner.compute_command(candidates)
            if cmd.action == ACTION_DO_NOTHING:
                continue
            if cmd.action == ACTION_RETRY:
                return False
            self.execute_command(deprovisioner, cmd)
            return True
        self.cluster.set_consolidated(True)
        return False

    def execute_command(self, deprovisioner, cmd: Command) -> None:
        """controller.go:143-194."""
        self.actions.inc({"action": f"{deprovisioner}/{cmd.action}"})
        LOG.info(
            "deprovisioning command", deprovisioner=str(deprovisioner),
            action=cmd.action,
            nodes=[n.metadata.name for n in cmd.nodes_to_remove],
            replacements=len(cmd.replacement_machines or ()),
        )
        if cmd.action == ACTION_REPLACE:
            if not self._launch_replacements(cmd, str(deprovisioner)):
                return
        for node in cmd.nodes_to_remove:
            if self.recorder:
                self.recorder.deprovisioning_terminating(node.metadata.name, str(cmd))
            try:
                self.kube_client.delete("Node", "", node.metadata.name)
                NODES_TERMINATED.inc({"reason": str(deprovisioner)})
            except Exception:
                pass
        self._wait_for_deletion(cmd.nodes_to_remove)

    def _launch_replacements(self, cmd: Command, reason: str) -> bool:
        """controller.go:198-253: cordon first, launch, wait for the
        replacements to initialize; roll back cordons on failure."""
        names = [n.metadata.name for n in cmd.nodes_to_remove]
        self._set_unschedulable(names, True)
        launched = self.provisioning.launch_machines(cmd.replacement_machines)
        if any(not n for n in launched):
            self._set_unschedulable(names, False)
            return False
        NODES_CREATED.inc({"reason": "deprovisioning"}, len(launched))
        self.cluster.mark_for_deletion(*names)
        deadline = self.clock() + self.readiness_wait
        while True:
            ready = all(self._initialized(name) for name in launched)
            if ready:
                return True
            if self.clock() >= deadline:
                # roll back (controller.go:246-251)
                self.cluster.unmark_for_deletion(*names)
                self._set_unschedulable(names, False)
                return False
            if self.clock is time.time:
                time.sleep(self.readiness_poll)
            else:
                return True  # fake clocks: tests drive initialization

    def _initialized(self, node_name: str) -> bool:
        from karpenter_core_tpu.api.labels import LABEL_NODE_INITIALIZED

        node = self.kube_client.get("Node", "", node_name)
        return node is not None and node.metadata.labels.get(LABEL_NODE_INITIALIZED) == "true"

    def _wait_for_deletion(self, nodes: List) -> None:
        """controller.go:175-194 (bounded poll; fake clocks skip)."""
        if self.clock is not time.time:
            return
        deadline = self.clock() + 30.0
        for node in nodes:
            while self.clock() < deadline:
                if self.kube_client.get("Node", "", node.metadata.name) is None:
                    break
                time.sleep(0.1)

    def _set_unschedulable(self, names: List[str], unschedulable: bool) -> None:
        for name in names:
            node = self.kube_client.get("Node", "", name)
            if node is None:
                continue
            if not unschedulable and node.metadata.deletion_timestamp is not None:
                continue
            if node.spec.unschedulable == unschedulable:
                continue
            node.spec.unschedulable = unschedulable
            self.kube_client.update(node)
