"""Deprovisioning core: Command/CandidateNode types, candidate scanning,
scheduling simulation, eviction-cost model, price filters, PDB limits.

Mirrors reference pkg/controllers/deprovisioning/{types,helpers,pdblimits}.go.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_TOPOLOGY_ZONE,
    Node,
    Pod,
)
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.solver.tpu_solver import SolvedMachine, SolveResult
from karpenter_core_tpu.utils import podutils

ACTION_DELETE = "delete"
ACTION_REPLACE = "replace"
ACTION_RETRY = "retry"
ACTION_DO_NOTHING = "do-nothing"

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


class CandidateNodeDeletingError(Exception):
    pass


@dataclass
class CandidateNode:
    """types.go:118-126."""

    node: Node
    state_node: object
    instance_type: InstanceType
    capacity_type: str
    zone: str
    provisioner: Provisioner
    pods: List[Pod]
    disruption_cost: float

    @property
    def name(self) -> str:
        return self.node.metadata.name


@dataclass
class Command:
    """types.go:63-67."""

    nodes_to_remove: List[Node] = field(default_factory=list)
    action: str = ACTION_DO_NOTHING
    replacement_machines: List[SolvedMachine] = field(default_factory=list)
    # provenance: True when a DELETE was issued straight from the vmapped
    # ladder screen (no exact confirming solve); a validation rejection of
    # such a command flips the next ladder to exact per-rung confirmation
    from_screen: bool = False

    def __str__(self) -> str:
        names = [n.metadata.name for n in self.nodes_to_remove]
        if self.action == ACTION_REPLACE:
            return f"{self.action}, terminating {names} and launching replacement"
        return f"{self.action}, terminating {names}"


# ---------------------------------------------------------------------------
# eviction cost model (helpers.go:115-155)


def pod_eviction_cost(pod: Pod) -> float:
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / (2.0**27)
        except ValueError:
            pass
    if pod.spec.priority is not None:
        cost += float(pod.spec.priority) / (2.0**25)
    return clamp(-10.0, cost, 10.0)


def disruption_cost(pods: List[Pod]) -> float:
    return sum(pod_eviction_cost(p) for p in pods)


def clamp(lo: float, v: float, hi: float) -> float:
    return max(lo, min(v, hi))


def lifetime_remaining(candidate: CandidateNode, clock=None) -> float:
    """helpers.go:308-318: fraction of expiry TTL left scales disruption
    cost toward 0 for nearly-expired nodes.

    clock resolves at CALL time (None -> time.time): a module-level
    `clock=time.time` default binds the function object at import, so a
    clock installed later (tests monkeypatching time.time, a fake clock
    threaded most-of-the-way) silently never reaches this comparison
    against the node's wall-clock creation_timestamp — the import-time-
    bound-clock pattern `make lint`'s monotonic-time pass now rejects."""
    if candidate.provisioner.spec.ttl_seconds_until_expired is None:
        return 1.0
    if clock is None:
        clock = time.time
    total = float(candidate.provisioner.spec.ttl_seconds_until_expired)
    age = clock() - candidate.node.metadata.creation_timestamp
    return clamp(0.0, (total - age) / total, 1.0)


# ---------------------------------------------------------------------------
# price filters (helpers.go:138-147,281-304)


def worst_launch_price(offerings: List[Offering], reqs: Requirements) -> float:
    """Max price the launch could resolve to: spot offerings if spot allowed,
    else on-demand."""
    ct_req = reqs.get_requirement(api_labels.LABEL_CAPACITY_TYPE)
    zone_req = reqs.get_requirement(LABEL_TOPOLOGY_ZONE)
    if ct_req.has(api_labels.CAPACITY_TYPE_SPOT):
        spot = [
            o
            for o in offerings
            if o.capacity_type == api_labels.CAPACITY_TYPE_SPOT and zone_req.has(o.zone)
        ]
        if spot:
            return max(o.price for o in spot)
    if ct_req.has(api_labels.CAPACITY_TYPE_ON_DEMAND):
        od = [
            o
            for o in offerings
            if o.capacity_type == api_labels.CAPACITY_TYPE_ON_DEMAND and zone_req.has(o.zone)
        ]
        if od:
            return max(o.price for o in od)
    return math.inf


def filter_by_price(
    options: List[InstanceType], reqs: Requirements, price: float
) -> List[InstanceType]:
    return [
        it for it in options if worst_launch_price(it.offerings.available(), reqs) < price
    ]


def instance_types_are_subset(lhs: List[InstanceType], rhs: List[InstanceType]) -> bool:
    rhs_names = {it.name for it in rhs}
    return all(it.name in rhs_names for it in lhs)


def node_prices(candidates: List[CandidateNode]) -> float:
    """Sum of the candidates' current offering prices (consolidation.go
    getNodePrices)."""
    total = 0.0
    for c in candidates:
        offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
        if offering is None:
            raise ValueError(
                f"unable to determine offering for {c.instance_type.name}/{c.capacity_type}/{c.zone}"
            )
        total += offering.price
    return total


def candidate_price(candidate: CandidateNode) -> Optional[float]:
    """One candidate's current offering price, or None when its offering
    cannot be determined (a 'priceless' node — its zone/capacity-type
    labels name an offering the cloud provider no longer lists). The
    ranking objective treats None as a zero-savings contribution; the
    exact REPLACE path still refuses to price such a subset
    (node_prices raises -> do-nothing, the reference's err branch)."""
    offering = candidate.instance_type.offerings.get(
        candidate.capacity_type, candidate.zone
    )
    return None if offering is None else offering.price


def replacement_price_floor(
    instance_types: Dict[str, List[InstanceType]]
) -> float:
    """The cheapest price ANY replacement launch could possibly resolve to:
    min over the live instance-type universe of worst_launch_price under
    unconstrained requirements. An optimistic lower bound on a REPLACE
    subset's replacement cost, used only to RANK subsets by savings
    (deprovisioning.replan objective) — the exact confirming solve still
    applies filter_by_price's strictly-cheaper rule before anything
    executes, so an over-optimistic rank costs one extra confirmation,
    never a wrong command."""
    floor = math.inf
    empty = Requirements()
    for its in instance_types.values():
        for it in its:
            price = worst_launch_price(it.offerings.available(), empty)
            floor = min(floor, price)
    return 0.0 if floor is math.inf else floor


# ---------------------------------------------------------------------------
# PDB limits (pdblimits.go:34-76)


class PDBLimits:
    def __init__(self, kube_client):
        self.kube_client = kube_client
        self.pdbs = kube_client.list("PodDisruptionBudget")

    def can_evict_pods(self, pods: List[Pod]) -> Tuple[str, bool]:
        """(blocking pdb name, ok)."""
        for pdb in self.pdbs:
            if pdb.spec.selector is None:
                continue
            for pod in pods:
                if pdb.metadata.namespace != pod.metadata.namespace:
                    continue
                if pdb.spec.selector.matches(pod.metadata.labels):
                    if pdb.status.disruptions_allowed <= 0:
                        return f"{pdb.metadata.namespace}/{pdb.metadata.name}", False
        return "", True


def pods_prevent_eviction(pods: List[Pod]) -> Tuple[str, bool]:
    """helpers.go PodsPreventEviction: do-not-evict blocks (reason, blocked)."""
    for pod in pods:
        if podutils.is_terminating(pod) or podutils.is_terminal(pod) or podutils.is_owned_by_node(pod):
            continue
        if podutils.has_do_not_evict(pod):
            return (
                f"pod {pod.metadata.namespace}/{pod.metadata.name} has do-not-evict annotation",
                True,
            )
    return "", False


def can_be_terminated(candidate: CandidateNode, pdbs: PDBLimits) -> Tuple[str, bool]:
    """helpers.go canBeTerminated."""
    if candidate.node.metadata.deletion_timestamp is not None:
        return "in the process of deletion", False
    pdb, ok = pdbs.can_evict_pods(candidate.pods)
    if not ok:
        return f"pdb {pdb} prevents pod evictions", False
    reason, blocked = pods_prevent_eviction(candidate.pods)
    if blocked:
        return reason, False
    return "", True


# ---------------------------------------------------------------------------
# candidate scan (helpers.go:161-238)


def candidate_nodes(
    cluster,
    kube_client,
    cloud_provider,
    should_deprovision: Callable[[object, Provisioner, List[Pod]], bool],
    clock=None,
) -> List[CandidateNode]:
    # clock resolves late (see lifetime_remaining): a default bound at
    # import would pin whatever time.time was at import forever
    if clock is None:
        clock = time.time
    provisioners: Dict[str, Provisioner] = {
        p.name: p for p in kube_client.list("Provisioner")
    }
    instance_types_by_prov: Dict[str, Dict[str, InstanceType]] = {
        name: {it.name: it for it in cloud_provider.get_instance_types(p)}
        for name, p in provisioners.items()
    }

    candidates: List[CandidateNode] = []

    # ONE pass over the pod store instead of a per-candidate filtered list:
    # the naive form is O(nodes x pods) with a lambda per pair — at 1k
    # nodes / 10k pods that is 10M calls per deprovisioning scan.
    # Shared references (copy_objects=False): this path only READS pods —
    # simulate paths shallow-clone (clone_for_simulation) before clearing
    # node_name and the solvers deep-copy a pod before relaxing it — and at
    # 10k pods the per-scan clone dominated the whole replan's host time
    pods_by_node: Dict[str, List[Pod]] = {}
    for p in kube_client.list("Pod", copy_objects=False):
        if p.spec.node_name and not podutils.is_terminal(p):
            pods_by_node.setdefault(p.spec.node_name, []).append(p)

    def visit(state_node) -> bool:
        labels = state_node.labels()
        prov_name = labels.get(api_labels.PROVISIONER_NAME_LABEL_KEY)
        provisioner = provisioners.get(prov_name)
        it_map = instance_types_by_prov.get(prov_name)
        if state_node.is_marked_for_deletion():
            return True
        if provisioner is None or it_map is None:
            return True
        instance_type = it_map.get(labels.get(LABEL_INSTANCE_TYPE_STABLE, ""))
        if instance_type is None:
            return True
        capacity_type = labels.get(api_labels.LABEL_CAPACITY_TYPE)
        zone = labels.get(LABEL_TOPOLOGY_ZONE)
        if not capacity_type or not zone:
            return True
        if not state_node.initialized():
            return True
        if state_node.nominated():
            return True
        if state_node.node is None:
            return True
        pods = pods_by_node.get(state_node.name(), [])
        if not should_deprovision(state_node, provisioner, pods):
            return True
        candidate = CandidateNode(
            node=state_node.node,
            state_node=state_node,
            instance_type=instance_type,
            capacity_type=capacity_type,
            zone=zone,
            provisioner=provisioner,
            pods=pods,
            disruption_cost=disruption_cost(pods),
        )
        candidate.disruption_cost *= lifetime_remaining(candidate, clock)
        candidates.append(candidate)
        return True

    cluster.for_each_node(visit)
    return candidates


# ---------------------------------------------------------------------------
# scheduling simulation (helpers.go:41-105)


def simulate_scheduling(
    kube_client,
    cluster,
    provisioning,
    candidates: List[CandidateNode],
) -> Tuple[List[SolvedMachine], bool]:
    """Re-enter the solver in simulation mode over (pending + evicted) pods
    with the candidates removed from the snapshot. Returns (new machines,
    all_pods_scheduled)."""
    from karpenter_core_tpu.obs import TRACER
    from karpenter_core_tpu.obs.flightrec import suppress_recording

    # suppress_recording: simulation re-entries must not churn the flight
    # recorder's ring (independent of whether tracing is enabled; the span
    # below only labels the metric series)
    with TRACER.span(
        "deprovisioning.simulate", candidates=len(candidates)
    ), suppress_recording():
        return _simulate_scheduling_traced(
            kube_client, cluster, provisioning, candidates
        )


def _simulate_scheduling_traced(
    kube_client,
    cluster,
    provisioning,
    candidates: List[CandidateNode],
) -> Tuple[List[SolvedMachine], bool]:
    candidate_names = {c.name for c in candidates}
    state_nodes = []
    deleting_nodes = []
    for node in cluster.nodes():
        if node.is_marked_for_deletion():
            deleting_nodes.append(node)
        elif node.name() not in candidate_names:
            state_nodes.append(node)
    if any(n.name() in candidate_names for n in deleting_nodes):
        raise CandidateNodeDeletingError()

    pods = provisioning.get_pending_pods()
    for candidate in candidates:
        pods.extend(
            p for p in candidate.pods if not podutils.is_owned_by_daemonset(p)
        )
    for node in deleting_nodes:
        pods.extend(
            p
            for p in kube_client.list(
                "Pod",
                field_filter=lambda p, n=node: p.spec.node_name == n.name(),
                copy_objects=False,  # cloned for mutation two lines down
            )
            if not podutils.is_terminal(p) and not podutils.is_owned_by_daemonset(p)
        )
    pods = [podutils.clone_for_simulation(p) for p in pods]

    provisioners = [
        p for p in kube_client.list("Provisioner") if p.metadata.deletion_timestamp is None
    ]
    if not provisioners:
        return [], not pods
    instance_types = {
        p.name: provisioning.cloud_provider.get_instance_types(p) for p in provisioners
    }
    result: SolveResult = provisioning.solver.solve(
        pods,
        provisioners,
        instance_types,
        daemonset_pods=provisioning.get_daemonset_pods(),
        state_nodes=state_nodes,
        kube_client=kube_client,
        cluster=cluster,
    )
    scheduled = result.pod_count_new() + result.pod_count_existing()
    # in-flight (uninitialized) existing nodes taking pods -> not conclusive
    for state_node, placed in result.existing_assignments:
        if placed and not state_node.initialized():
            return result.new_machines, False
    return result.new_machines, scheduled == len(pods)
