"""Expiration, Drift, and TTL-based Emptiness deprovisioners.

Mirrors reference pkg/controllers/deprovisioning/{expiration,drift,
emptiness}.go.
"""
from __future__ import annotations

import time
from typing import List

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.settings import current as current_settings
from karpenter_core_tpu.controllers.deprovisioning.consolidation import Consolidation
from karpenter_core_tpu.controllers.deprovisioning.core import (
    ACTION_DELETE,
    ACTION_DO_NOTHING,
    ACTION_REPLACE,
    CandidateNode,
    CandidateNodeDeletingError,
    Command,
    PDBLimits,
    can_be_terminated,
    simulate_scheduling,
)

FAR_FUTURE = 1e18


class Expiration(Consolidation):
    """expiration.go:44-120: TTLSecondsUntilExpired-ordered replacement;
    proceeds even if not all pods reschedule."""

    def __str__(self) -> str:
        return "expiration"

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        return self.clock() > expiration_time(state_node, provisioner)

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        candidates = sorted(
            candidates, key=lambda c: expiration_time(c.state_node, c.provisioner)
        )
        pdbs = PDBLimits(self.kube_client)
        for candidate in candidates:
            _, ok = can_be_terminated(candidate, pdbs)
            if not ok:
                continue
            try:
                new_machines, _all_scheduled = simulate_scheduling(
                    self.kube_client, self.cluster, self.provisioning, [candidate]
                )
            except CandidateNodeDeletingError:
                continue
            if not new_machines:
                return Command(nodes_to_remove=[candidate.node], action=ACTION_DELETE)
            return Command(
                nodes_to_remove=[candidate.node],
                action=ACTION_REPLACE,
                replacement_machines=new_machines,
            )
        return Command(action=ACTION_DO_NOTHING)


def expiration_time(state_node, provisioner) -> float:
    if provisioner is None or provisioner.spec.ttl_seconds_until_expired is None:
        return FAR_FUTURE
    created = (
        state_node.node.metadata.creation_timestamp
        if state_node.node is not None
        else (state_node.machine.metadata.creation_timestamp if state_node.machine else 0.0)
    )
    return created + float(provisioner.spec.ttl_seconds_until_expired)


class Drift(Consolidation):
    """drift.go:40-103: feature-gated; acts on nodes annotated
    voluntary-disruption=drifted."""

    def __str__(self) -> str:
        return "drift"

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        if not current_settings().drift_enabled:
            return False
        return (
            state_node.annotations().get(api_labels.VOLUNTARY_DISRUPTION_ANNOTATION_KEY)
            == api_labels.VOLUNTARY_DISRUPTION_DRIFTED_VALUE
        )

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        pdbs = PDBLimits(self.kube_client)
        for candidate in candidates:
            _, ok = can_be_terminated(candidate, pdbs)
            if not ok:
                continue
            try:
                new_machines, all_scheduled = simulate_scheduling(
                    self.kube_client, self.cluster, self.provisioning, [candidate]
                )
            except CandidateNodeDeletingError:
                continue
            if not all_scheduled:
                continue
            if not new_machines:
                return Command(nodes_to_remove=[candidate.node], action=ACTION_DELETE)
            return Command(
                nodes_to_remove=[candidate.node],
                action=ACTION_REPLACE,
                replacement_machines=new_machines,
            )
        return Command(action=ACTION_DO_NOTHING)


class Emptiness(Consolidation):
    """emptiness.go:44-127 (TTL path): delete nodes whose emptiness
    timestamp + TTLSecondsAfterEmpty elapsed. Works independently of the
    consolidation feature."""

    def __str__(self) -> str:
        return "emptiness"

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        if provisioner is None or provisioner.spec.ttl_seconds_after_empty is None:
            return False
        raw = state_node.annotations().get(api_labels.EMPTINESS_TIMESTAMP_ANNOTATION_KEY)
        if raw is None:
            return False
        try:
            emptiness_time = float(raw)
        except ValueError:
            return False
        return self.clock() > emptiness_time + float(provisioner.spec.ttl_seconds_after_empty)

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        empty = [c for c in candidates if not [
            p for p in c.pods if not _is_daemon(p)
        ]]
        if not empty:
            return Command(action=ACTION_DO_NOTHING)
        return Command(nodes_to_remove=[c.node for c in empty], action=ACTION_DELETE)


def _is_daemon(pod) -> bool:
    from karpenter_core_tpu.utils import podutils

    return podutils.is_owned_by_daemonset(pod)
