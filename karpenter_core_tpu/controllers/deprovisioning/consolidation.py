"""Consolidation deprovisioners: base logic + empty/multi/single-node.

Mirrors reference pkg/controllers/deprovisioning/{consolidation,
emptynodeconsolidation,multinodeconsolidation,singlenodeconsolidation,
validation}.go.

The multi-node search (reference: binary search over candidate prefixes,
O(log N) SEQUENTIAL simulated solves, multinodeconsolidation.go:87-113) is
replaced by a parallel prefix ladder: a geometric set of prefix sizes is
evaluated as independent solver dispatches and the largest feasible prefix
wins — the TPU replan path of BASELINE config 4.
"""
from __future__ import annotations

import math
import time
from typing import List, Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.controllers.deprovisioning.core import (
    ACTION_DELETE,
    ACTION_DO_NOTHING,
    ACTION_REPLACE,
    ACTION_RETRY,
    CandidateNode,
    CandidateNodeDeletingError,
    Command,
    PDBLimits,
    can_be_terminated,
    candidate_nodes,
    filter_by_price,
    instance_types_are_subset,
    node_prices,
    simulate_scheduling,
)
from karpenter_core_tpu.obs.log import get_logger
from karpenter_core_tpu.scheduling.requirement import OP_IN, Requirement

LOG = get_logger("karpenter.deprovisioning.consolidation")

CONSOLIDATION_TTL = 15.0  # consolidation.go:66


class Consolidation:
    """consolidation.go:36-110 (shared base)."""

    def __init__(self, kube_client, cluster, provisioning, cloud_provider, recorder,
                 clock=time.time, validation_ttl: float = CONSOLIDATION_TTL):
        self.kube_client = kube_client
        self.cluster = cluster
        self.provisioning = provisioning
        self.cloud_provider = cloud_provider
        self.recorder = recorder
        self.clock = clock
        self.validation_ttl = validation_ttl

    def __str__(self) -> str:
        return "consolidation"

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        """consolidation.go:89-104."""
        annotations = state_node.annotations()
        if api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY in annotations:
            return annotations[api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY] != "true"
        if provisioner is None:
            return False
        return bool(provisioner.spec.consolidation and provisioner.spec.consolidation.enabled)

    def sort_and_filter_candidates(self, candidates: List[CandidateNode]) -> List[CandidateNode]:
        """consolidation.go:69-87: PDB/do-not-evict gate, ascending
        disruption cost."""
        pdbs = PDBLimits(self.kube_client)
        out = []
        for candidate in candidates:
            reason, ok = can_be_terminated(candidate, pdbs)
            if not ok:
                if self.recorder:
                    self.recorder.deprovisioning_blocked("Node", candidate.name, reason)
                continue
            out.append(candidate)
        return sorted(out, key=lambda c: c.disruption_cost)

    def _disruption_budget(self) -> int:
        """The configured victims-per-pass cap (0 = unbounded):
        Settings.consolidation_disruption_budget bounds how many nodes any
        single consolidation command may terminate, so a large savings win
        can never drain more of the cluster in one pass than the operator
        signed up for."""
        from karpenter_core_tpu.api import settings as api_settings

        return api_settings.current().consolidation_disruption_budget

    def _any_relaxable(self, candidates: List[CandidateNode]) -> bool:
        """True when any involved pod (the candidates' or pending) still
        carries a relaxable soft constraint — a negative round-0 screen is
        inconclusive for those (scheduler.go:114-123 relaxes until
        exhaustion), so the exact (relaxing) path must confirm."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.preferences import (
            Preferences,
        )

        prefs = Preferences()
        pods = [p for c in candidates for p in c.pods]
        pods += list(self.provisioning.get_pending_pods())
        return any(prefs.is_relaxable(p) for p in pods)

    def _record_pass(self, candidates, screens, cmd: Command,
                     scenario=None) -> None:
        """Flight-record this consolidation decision (candidate set, every
        screened subset's verdict + objective, the chosen Command) so
        hack/replay.py can diff the device-ranked decision against the
        sequential simulator offline. Best-effort, like every recorder
        hook: a serialization failure must never break the pass."""
        from karpenter_core_tpu.obs import flightrec

        try:
            flightrec.FLIGHTREC.record_consolidation(
                type(self).__name__, candidates, screens, cmd,
                scenario=scenario,
            )
        except Exception:  # noqa: BLE001 — recording never breaks the pass
            pass

    def compute_consolidation(self, candidates: List[CandidateNode]) -> Command:
        """consolidation.go:180-264: delete if 0 replacements; replace if
        exactly 1 cheaper; spot->spot forbidden; OD->[OD,spot] forces spot."""
        try:
            new_machines, all_scheduled = simulate_scheduling(
                self.kube_client, self.cluster, self.provisioning, candidates
            )
        except CandidateNodeDeletingError:
            return Command(action=ACTION_DO_NOTHING)
        if not all_scheduled:
            self._blocked(candidates, "not all pods would schedule")
            return Command(action=ACTION_DO_NOTHING)
        if len(new_machines) == 0:
            return Command(
                nodes_to_remove=[c.node for c in candidates], action=ACTION_DELETE
            )
        if len(new_machines) != 1:
            self._blocked(
                candidates, f"can't remove without creating {len(new_machines)} nodes"
            )
            return Command(action=ACTION_DO_NOTHING)

        replacement = new_machines[0]
        try:
            current_price = node_prices(candidates)
        except ValueError:
            # a candidate's current offering is unknown (priceless node):
            # the reference's getNodePrices err branch — block the REPLACE
            # (deletes never price and returned above)
            self._blocked(candidates, "unable to determine node prices")
            return Command(action=ACTION_DO_NOTHING)
        replacement.instance_type_options = filter_by_price(
            replacement.instance_type_options, replacement.requirements, current_price
        )
        if not replacement.instance_type_options:
            self._blocked(candidates, "can't replace with a cheaper node")
            return Command(action=ACTION_DO_NOTHING)

        all_spot = all(c.capacity_type == api_labels.CAPACITY_TYPE_SPOT for c in candidates)
        ct_req = replacement.requirements.get_requirement(api_labels.LABEL_CAPACITY_TYPE)
        if all_spot and ct_req.has(api_labels.CAPACITY_TYPE_SPOT):
            self._blocked(candidates, "can't replace a spot node with a spot node")
            return Command(action=ACTION_DO_NOTHING)
        # OD->[OD,spot] flexibility forces the spot side (consolidation.go:246-251)
        if ct_req.has(api_labels.CAPACITY_TYPE_SPOT) and ct_req.has(
            api_labels.CAPACITY_TYPE_ON_DEMAND
        ):
            replacement.requirements.add(
                Requirement(
                    api_labels.LABEL_CAPACITY_TYPE, OP_IN, [api_labels.CAPACITY_TYPE_SPOT]
                )
            )
        return Command(
            nodes_to_remove=[c.node for c in candidates],
            action=ACTION_REPLACE,
            replacement_machines=new_machines,
        )

    def validate_command(self, cmd: Command, candidates: List[CandidateNode]) -> bool:
        """consolidation.go:114-175: re-simulation invariants after TTL."""
        names = {n.metadata.name for n in cmd.nodes_to_remove}
        to_delete = [c for c in candidates if c.name in names]
        if not to_delete:
            return False
        try:
            new_machines, all_scheduled = simulate_scheduling(
                self.kube_client, self.cluster, self.provisioning, to_delete
            )
        except CandidateNodeDeletingError:
            return False
        if not all_scheduled:
            return False
        if len(new_machines) == 0:
            return len(cmd.replacement_machines) == 0
        if len(new_machines) > 1:
            return False
        if not cmd.replacement_machines:
            return False
        return instance_types_are_subset(
            cmd.replacement_machines[0].instance_type_options,
            new_machines[0].instance_type_options,
        )

    def validate_after_ttl(self, cmd: Command) -> bool:
        """validation.go:63-103: wait the TTL, re-scan candidates, nominated
        nodes block, re-validate."""
        self._wait(self.validation_ttl)
        candidates = candidate_nodes(
            self.cluster,
            self.kube_client,
            self.cloud_provider,
            self.should_deprovision,
            self.clock,
        )
        names = {n.metadata.name for n in cmd.nodes_to_remove}
        remaining = [c for c in candidates if c.name in names]
        if len(remaining) != len(names):
            return False
        for candidate in remaining:
            if candidate.state_node.nominated():
                return False
        return self.validate_command(cmd, remaining)

    def _wait(self, seconds: float) -> None:
        """Clock-driven TTL wait (validation.go:60-67). Under the real clock
        this sleeps; under a steppable test clock (anything exposing
        `.sleep`, e.g. testing.FakeClock) it blocks until the clock is
        ADVANCED past the deadline by another thread — the same contract as
        the reference's clock.Sleep on a FakeClock, so the 15s revalidation
        window is actually exercised in tests instead of no-opped. A bare
        callable clock with neither wall-time nor step semantics waits
        nothing."""
        if seconds <= 0:
            return
        sleep = getattr(self.clock, "sleep", None)
        if sleep is not None:
            sleep(seconds)
        elif self.clock is time.time:
            time.sleep(seconds)

    def _blocked(self, candidates: List[CandidateNode], reason: str) -> None:
        if self.recorder and len(candidates) == 1:
            self.recorder.deprovisioning_blocked("Node", candidates[0].name, reason)


class EmptyNodeConsolidation(Consolidation):
    """emptynodeconsolidation.go:44-94."""

    def __str__(self) -> str:
        return "emptiness"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if self.cluster.consolidated():
            return Command(action=ACTION_DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        empty = [c for c in candidates if not c.pods]
        budget = self._disruption_budget()
        if budget:
            # victims-per-pass cap (ascending disruption cost — the sort
            # above): the remainder re-enters the next reconcile pass
            empty = empty[:budget]
        if not empty:
            return Command(action=ACTION_DO_NOTHING)
        cmd = Command(nodes_to_remove=[c.node for c in empty], action=ACTION_DELETE)
        # revalidate after TTL: still empty and not nominated
        self._wait(self.validation_ttl)
        revalidated = candidate_nodes(
            self.cluster, self.kube_client, self.cloud_provider,
            self.should_deprovision, self.clock,
        )
        names = {n.metadata.name for n in cmd.nodes_to_remove}
        for candidate in revalidated:
            if candidate.name in names and candidate.pods and not candidate.state_node.nominated():
                return Command(action=ACTION_RETRY)
        return cmd


class MultiNodeConsolidation(Consolidation):
    """multinodeconsolidation.go:42-166, with the parallel prefix ladder in
    place of binary search."""

    LADDER_POINTS = 8

    def __str__(self) -> str:
        return "consolidation"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if self.cluster.consolidated():
            return Command(action=ACTION_DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        cmd = self.first_n_consolidation_ladder(candidates)
        if cmd.action == ACTION_DO_NOTHING:
            return cmd
        if not self.validate_after_ttl(cmd):
            # If the rejected command came straight from the vmapped screen
            # (the delete shortcut), force the NEXT ladder through exact
            # per-rung confirmation: a screen/exact disagreement would
            # otherwise reproduce the identical screen answer every loop —
            # a retry livelock that also shadows smaller, genuinely
            # feasible rungs.
            if cmd.from_screen:
                self._confirm_deletes_once = True
            return Command(action=ACTION_RETRY)
        return cmd

    def first_n_consolidation_ladder(self, candidates: List[CandidateNode]) -> Command:
        """Evaluate a geometric ladder of prefix sizes (plus the all-empty
        subset); keep the best by the savings objective. Replaces the
        reference's sequential binary search
        (multinodeconsolidation.go:87-113).

        On a solver with batched-replan support (TPUSolver), every subset
        is screened in ONE batched device dispatch over a shared union
        encode (solver/replan.py), and feasible subsets rank by REAL
        savings (current node prices minus the replacement floor) with
        disruption cost as the tie-break — not first-feasible-prefix. A
        conclusive 0-new-machine winner becomes the DELETE command
        directly (validate_after_ttl re-simulates through the exact path
        before execution; a validation rejection flips the next ladder
        back to exact per-subset confirmation); REPLACE winners are always
        confirmed through the exact solve path, stepping down the ranking
        on disagreement. Without batched-replan support each prefix rung
        is a full solve (host fallback). The configured disruption budget
        (api/settings.py) caps victims per pass on both paths."""
        if len(candidates) < 2:
            return Command(action=ACTION_DO_NOTHING)
        n = len(candidates)
        budget = self._disruption_budget()
        if budget:
            n = min(n, budget)
        if n < 2:
            return Command(action=ACTION_DO_NOTHING)
        sizes = sorted(
            {
                max(2, min(n, round(n ** (i / (self.LADDER_POINTS - 1)))))
                for i in range(self.LADDER_POINTS)
            }
        ) if n > 2 else [2]

        if getattr(self.provisioning.solver, "supports_batched_replan", False):
            return self._ladder_batched(candidates, sizes)
        return self._ladder_sequential(candidates, sizes)

    def _ladder_sequential(self, candidates: List[CandidateNode],
                           sizes: List[int]) -> Command:
        """The host fallback: one exact solve per prefix rung, keep the
        largest actionable (the pre-batched behavior, and the degrade path
        when the batched screen itself fails)."""
        best = Command(action=ACTION_DO_NOTHING)
        for size in sizes:
            cmd = self._evaluate_prefix(candidates, size)
            if cmd.action in (ACTION_REPLACE, ACTION_DELETE):
                best = cmd
            else:
                break  # larger prefixes are monotonically harder
        return best

    def _evaluate_subset(self, subset: List[CandidateNode]) -> Command:
        """Exact evaluation of one candidate subset: full solve +
        price/same-type rules."""
        cmd = self.compute_consolidation(subset)
        if cmd.action == ACTION_REPLACE:
            cmd.replacement_machines[0].instance_type_options = self._filter_out_same_type(
                cmd.replacement_machines[0], subset
            )
            if not cmd.replacement_machines[0].instance_type_options:
                cmd = Command(action=ACTION_DO_NOTHING)
        return cmd

    def _evaluate_prefix(self, candidates: List[CandidateNode], size: int) -> Command:
        return self._evaluate_subset(candidates[:size])

    def _ladder_batched(self, candidates: List[CandidateNode],
                        sizes: List[int]) -> Command:
        """One batched screen over the prefix rungs + the all-empty-nodes
        subset; feasible subsets rank by (savings desc, disruption asc,
        size desc). Conclusive 0-new-machine winners short-circuit to
        DELETE, REPLACE winners get exact confirmation (price and
        same-type rules live there), stepping down the ranking on
        disagreement. See first_n_consolidation_ladder for the validation
        backstop on the delete shortcut."""
        from karpenter_core_tpu.solver.replan import batched_subset_screen

        confirm_deletes = getattr(self, "_confirm_deletes_once", False)
        subsets = [tuple(range(s)) for s in sizes]
        prefix_count = len(subsets)
        # ride-along emptiness subset: all pod-free candidates in one
        # DELETE — a non-contiguous subset the prefix ladder would only
        # find if the empties happened to sort first (they usually do —
        # zero pods is zero disruption cost — but PDB/price ordering can
        # interleave); free to screen, and it exercises the evaluator's
        # arbitrary-subset encoding on every pass
        budget = self._disruption_budget()
        empty_idx = tuple(
            i for i, c in enumerate(candidates) if not c.pods
        )[: budget or None]
        if len(empty_idx) >= 2 and empty_idx not in set(subsets):
            subsets.append(empty_idx)
        try:
            screens, scenario = batched_subset_screen(
                self.kube_client, self.cluster, self.provisioning, candidates,
                subsets, max_nodes=getattr(
                    self.provisioning.solver, "max_nodes", 1024
                ),
            )
        except CandidateNodeDeletingError:
            # transient (a candidate is mid-delete): keep the one-shot flag
            # so the NEXT successful ladder still runs exact confirmation
            return Command(action=ACTION_DO_NOTHING)
        except Exception as exc:  # noqa: BLE001 — screen is an optimization
            # a solver/RPC fault (remote replan unreachable, breaker open,
            # device error) must degrade to the sequential simulate path —
            # the parity oracle kept for exactly this — never crash the
            # deprovisioning reconcile loop
            LOG.warning(
                "batched consolidation screen failed; sequential fallback",
                error=type(exc).__name__, error_detail=str(exc)[:200],
            )
            return self._ladder_sequential(candidates, sizes)
        self._confirm_deletes_once = False
        feasible = [
            s for s in screens
            if s.all_scheduled and s.conclusive and s.n_new_machines <= 1
        ]
        ranked = sorted(
            feasible, key=lambda s: (-s.savings, s.disruption, -s.size)
        )
        cmd = Command(action=ACTION_DO_NOTHING)
        for screen in ranked:
            subset = [candidates[i] for i in screen.subset]
            # A conclusive 0-new-machine subset IS the delete decision: the
            # screen ran the same round-0 kernel the exact path would (the
            # delete branch of consolidation.go:180-264 checks only "all
            # scheduled, zero replacements" — price/spot/same-type rules
            # exist only for REPLACE), relaxation could only make pods MORE
            # schedulable, and validate_after_ttl re-simulates through the
            # exact path before any node is touched. Skipping the
            # confirming solve here halves the replan's critical path.
            # confirm_deletes (set after a validation rejection of a
            # screen-sourced delete) routes every subset through the exact
            # path instead, restoring the step-down on disagreement.
            if screen.n_new_machines == 0 and not confirm_deletes:
                cmd = Command(
                    nodes_to_remove=[c.node for c in subset],
                    action=ACTION_DELETE,
                    from_screen=True,
                )
                break
            exact = self._evaluate_subset(subset)
            if exact.action in (ACTION_REPLACE, ACTION_DELETE):
                cmd = exact
                break
        else:
            # The screen is the round-0 kernel only — no preference
            # relaxation (scheduler.go:114-123 relaxes until exhaustion).
            # A negative screen is therefore inconclusive when any
            # involved pod still carries a relaxable soft constraint;
            # confirm those prefix rungs through the exact (relaxing) path
            # before concluding nothing consolidates.
            feasible_ids = {s.subset for s in feasible}
            blocked = [
                s for s in sizes if tuple(range(s)) not in feasible_ids
            ]
            if blocked and self._any_relaxable(candidates[: blocked[-1]]):
                for size in blocked:
                    exact = self._evaluate_prefix(candidates, size)
                    if exact.action in (ACTION_REPLACE, ACTION_DELETE):
                        cmd = exact
                    else:
                        break
        self._record_pass(candidates, screens, cmd, scenario=scenario)
        return cmd

    def _filter_out_same_type(self, replacement, consolidated: List[CandidateNode]):
        """multinodeconsolidation.go:133-166: prevent replacing with the same
        instance type unless strictly cheaper than the cheapest existing use
        of that type."""
        existing_types = set()
        prices_by_type = {}
        for c in consolidated:
            existing_types.add(c.instance_type.name)
            offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
            if offering is not None:
                prices_by_type[c.instance_type.name] = min(
                    prices_by_type.get(c.instance_type.name, math.inf), offering.price
                )
        max_price = math.inf
        for it in replacement.instance_type_options:
            if it.name in existing_types:
                max_price = min(max_price, prices_by_type.get(it.name, math.inf))
        return filter_by_price(
            replacement.instance_type_options, replacement.requirements, max_price
        )


class SingleNodeConsolidation(Consolidation):
    """singlenodeconsolidation.go:44-86, with the per-candidate simulation
    sweep replaced by the batched subset evaluator: every singleton subset
    screens in a few chunked device dispatches (solver/replan.py), and
    only the feasible candidates — ranked by savings — pay an exact
    confirming solve. The sequential sweep is kept verbatim as the
    fallback (no batched-replan solver) and as the screened-out backstop
    when relaxable pods make a negative screen inconclusive."""

    def __str__(self) -> str:
        return "consolidation"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if self.cluster.consolidated():
            return Command(action=ACTION_DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        order, screens, scenario = self._ranked_candidates(candidates)
        failed_validation = False
        final = Command(action=ACTION_DO_NOTHING)
        for candidate in order:
            cmd = self.compute_consolidation([candidate])
            if cmd.action in (ACTION_DO_NOTHING, ACTION_RETRY):
                continue
            if not self.validate_after_ttl(cmd):
                failed_validation = True
                continue
            final = cmd
            break
        if final.action == ACTION_DO_NOTHING and failed_validation:
            final = Command(action=ACTION_RETRY)
        if screens is not None:
            self._record_pass(candidates, screens, final, scenario=scenario)
        return final

    def _ranked_candidates(self, candidates: List[CandidateNode]):
        """(exact-confirmation order, screens, scenario): feasible
        singletons first, ranked by (savings desc, disruption asc);
        screened-out candidates are dropped UNLESS relaxable pods are in
        play (the screen is the round-0 kernel — a negative verdict is
        inconclusive for them), in which case they trail in the
        reference's original order. Falls back to the untouched candidate
        order (screens=None) when no batched-replan solver is attached or
        the screen fails — the screen is an optimization, never a
        correctness dependency."""
        if len(candidates) < 2 or not getattr(
            self.provisioning.solver, "supports_batched_replan", False
        ):
            return candidates, None, None
        from karpenter_core_tpu.solver.replan import batched_subset_screen

        try:
            screens, scenario = batched_subset_screen(
                self.kube_client, self.cluster, self.provisioning,
                candidates, [(i,) for i in range(len(candidates))],
                max_nodes=getattr(
                    self.provisioning.solver, "max_nodes", 1024
                ),
            )
        except CandidateNodeDeletingError:
            # transient: the sequential sweep handles the mid-delete
            # candidate per-simulation (compute_consolidation catches it)
            return candidates, None, None
        except Exception:
            return candidates, None, None
        feasible = [
            s for s in screens
            if s.all_scheduled and s.conclusive and s.n_new_machines <= 1
        ]
        feasible_ids = {id(s) for s in feasible}
        ranked = sorted(feasible, key=lambda s: (-s.savings, s.disruption))
        order = [candidates[s.subset[0]] for s in ranked]
        screened_out = [
            candidates[s.subset[0]] for s in screens
            if id(s) not in feasible_ids
        ]
        if screened_out and self._any_relaxable(screened_out):
            order += screened_out
        return order, screens, scenario
