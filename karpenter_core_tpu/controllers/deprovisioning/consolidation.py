"""Consolidation deprovisioners: base logic + empty/multi/single-node.

Mirrors reference pkg/controllers/deprovisioning/{consolidation,
emptynodeconsolidation,multinodeconsolidation,singlenodeconsolidation,
validation}.go.

The multi-node search (reference: binary search over candidate prefixes,
O(log N) SEQUENTIAL simulated solves, multinodeconsolidation.go:87-113) is
replaced by a parallel prefix ladder: a geometric set of prefix sizes is
evaluated as independent solver dispatches and the largest feasible prefix
wins — the TPU replan path of BASELINE config 4.
"""
from __future__ import annotations

import math
import time
from typing import List, Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.controllers.deprovisioning.core import (
    ACTION_DELETE,
    ACTION_DO_NOTHING,
    ACTION_REPLACE,
    ACTION_RETRY,
    CandidateNode,
    CandidateNodeDeletingError,
    Command,
    PDBLimits,
    can_be_terminated,
    candidate_nodes,
    filter_by_price,
    instance_types_are_subset,
    node_prices,
    simulate_scheduling,
)
from karpenter_core_tpu.scheduling.requirement import OP_IN, Requirement

CONSOLIDATION_TTL = 15.0  # consolidation.go:66


class Consolidation:
    """consolidation.go:36-110 (shared base)."""

    def __init__(self, kube_client, cluster, provisioning, cloud_provider, recorder,
                 clock=time.time, validation_ttl: float = CONSOLIDATION_TTL):
        self.kube_client = kube_client
        self.cluster = cluster
        self.provisioning = provisioning
        self.cloud_provider = cloud_provider
        self.recorder = recorder
        self.clock = clock
        self.validation_ttl = validation_ttl

    def __str__(self) -> str:
        return "consolidation"

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        """consolidation.go:89-104."""
        annotations = state_node.annotations()
        if api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY in annotations:
            return annotations[api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY] != "true"
        if provisioner is None:
            return False
        return bool(provisioner.spec.consolidation and provisioner.spec.consolidation.enabled)

    def sort_and_filter_candidates(self, candidates: List[CandidateNode]) -> List[CandidateNode]:
        """consolidation.go:69-87: PDB/do-not-evict gate, ascending
        disruption cost."""
        pdbs = PDBLimits(self.kube_client)
        out = []
        for candidate in candidates:
            reason, ok = can_be_terminated(candidate, pdbs)
            if not ok:
                if self.recorder:
                    self.recorder.deprovisioning_blocked("Node", candidate.name, reason)
                continue
            out.append(candidate)
        return sorted(out, key=lambda c: c.disruption_cost)

    def compute_consolidation(self, candidates: List[CandidateNode]) -> Command:
        """consolidation.go:180-264: delete if 0 replacements; replace if
        exactly 1 cheaper; spot->spot forbidden; OD->[OD,spot] forces spot."""
        try:
            new_machines, all_scheduled = simulate_scheduling(
                self.kube_client, self.cluster, self.provisioning, candidates
            )
        except CandidateNodeDeletingError:
            return Command(action=ACTION_DO_NOTHING)
        if not all_scheduled:
            self._blocked(candidates, "not all pods would schedule")
            return Command(action=ACTION_DO_NOTHING)
        if len(new_machines) == 0:
            return Command(
                nodes_to_remove=[c.node for c in candidates], action=ACTION_DELETE
            )
        if len(new_machines) != 1:
            self._blocked(
                candidates, f"can't remove without creating {len(new_machines)} nodes"
            )
            return Command(action=ACTION_DO_NOTHING)

        replacement = new_machines[0]
        current_price = node_prices(candidates)
        replacement.instance_type_options = filter_by_price(
            replacement.instance_type_options, replacement.requirements, current_price
        )
        if not replacement.instance_type_options:
            self._blocked(candidates, "can't replace with a cheaper node")
            return Command(action=ACTION_DO_NOTHING)

        all_spot = all(c.capacity_type == api_labels.CAPACITY_TYPE_SPOT for c in candidates)
        ct_req = replacement.requirements.get_requirement(api_labels.LABEL_CAPACITY_TYPE)
        if all_spot and ct_req.has(api_labels.CAPACITY_TYPE_SPOT):
            self._blocked(candidates, "can't replace a spot node with a spot node")
            return Command(action=ACTION_DO_NOTHING)
        # OD->[OD,spot] flexibility forces the spot side (consolidation.go:246-251)
        if ct_req.has(api_labels.CAPACITY_TYPE_SPOT) and ct_req.has(
            api_labels.CAPACITY_TYPE_ON_DEMAND
        ):
            replacement.requirements.add(
                Requirement(
                    api_labels.LABEL_CAPACITY_TYPE, OP_IN, [api_labels.CAPACITY_TYPE_SPOT]
                )
            )
        return Command(
            nodes_to_remove=[c.node for c in candidates],
            action=ACTION_REPLACE,
            replacement_machines=new_machines,
        )

    def validate_command(self, cmd: Command, candidates: List[CandidateNode]) -> bool:
        """consolidation.go:114-175: re-simulation invariants after TTL."""
        names = {n.metadata.name for n in cmd.nodes_to_remove}
        to_delete = [c for c in candidates if c.name in names]
        if not to_delete:
            return False
        try:
            new_machines, all_scheduled = simulate_scheduling(
                self.kube_client, self.cluster, self.provisioning, to_delete
            )
        except CandidateNodeDeletingError:
            return False
        if not all_scheduled:
            return False
        if len(new_machines) == 0:
            return len(cmd.replacement_machines) == 0
        if len(new_machines) > 1:
            return False
        if not cmd.replacement_machines:
            return False
        return instance_types_are_subset(
            cmd.replacement_machines[0].instance_type_options,
            new_machines[0].instance_type_options,
        )

    def validate_after_ttl(self, cmd: Command) -> bool:
        """validation.go:63-103: wait the TTL, re-scan candidates, nominated
        nodes block, re-validate."""
        self._wait(self.validation_ttl)
        candidates = candidate_nodes(
            self.cluster,
            self.kube_client,
            self.cloud_provider,
            self.should_deprovision,
            self.clock,
        )
        names = {n.metadata.name for n in cmd.nodes_to_remove}
        remaining = [c for c in candidates if c.name in names]
        if len(remaining) != len(names):
            return False
        for candidate in remaining:
            if candidate.state_node.nominated():
                return False
        return self.validate_command(cmd, remaining)

    def _wait(self, seconds: float) -> None:
        """Clock-driven TTL wait (validation.go:60-67). Under the real clock
        this sleeps; under a steppable test clock (anything exposing
        `.sleep`, e.g. testing.FakeClock) it blocks until the clock is
        ADVANCED past the deadline by another thread — the same contract as
        the reference's clock.Sleep on a FakeClock, so the 15s revalidation
        window is actually exercised in tests instead of no-opped. A bare
        callable clock with neither wall-time nor step semantics waits
        nothing."""
        if seconds <= 0:
            return
        sleep = getattr(self.clock, "sleep", None)
        if sleep is not None:
            sleep(seconds)
        elif self.clock is time.time:
            time.sleep(seconds)

    def _blocked(self, candidates: List[CandidateNode], reason: str) -> None:
        if self.recorder and len(candidates) == 1:
            self.recorder.deprovisioning_blocked("Node", candidates[0].name, reason)


class EmptyNodeConsolidation(Consolidation):
    """emptynodeconsolidation.go:44-94."""

    def __str__(self) -> str:
        return "emptiness"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if self.cluster.consolidated():
            return Command(action=ACTION_DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        empty = [c for c in candidates if not c.pods]
        if not empty:
            return Command(action=ACTION_DO_NOTHING)
        cmd = Command(nodes_to_remove=[c.node for c in empty], action=ACTION_DELETE)
        # revalidate after TTL: still empty and not nominated
        self._wait(self.validation_ttl)
        revalidated = candidate_nodes(
            self.cluster, self.kube_client, self.cloud_provider,
            self.should_deprovision, self.clock,
        )
        names = {n.metadata.name for n in cmd.nodes_to_remove}
        for candidate in revalidated:
            if candidate.name in names and candidate.pods and not candidate.state_node.nominated():
                return Command(action=ACTION_RETRY)
        return cmd


class MultiNodeConsolidation(Consolidation):
    """multinodeconsolidation.go:42-166, with the parallel prefix ladder in
    place of binary search."""

    LADDER_POINTS = 8

    def __str__(self) -> str:
        return "consolidation"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if self.cluster.consolidated():
            return Command(action=ACTION_DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        cmd = self.first_n_consolidation_ladder(candidates)
        if cmd.action == ACTION_DO_NOTHING:
            return cmd
        if not self.validate_after_ttl(cmd):
            # If the rejected command came straight from the vmapped screen
            # (the delete shortcut), force the NEXT ladder through exact
            # per-rung confirmation: a screen/exact disagreement would
            # otherwise reproduce the identical screen answer every loop —
            # a retry livelock that also shadows smaller, genuinely
            # feasible rungs.
            if cmd.from_screen:
                self._confirm_deletes_once = True
            return Command(action=ACTION_RETRY)
        return cmd

    def first_n_consolidation_ladder(self, candidates: List[CandidateNode]) -> Command:
        """Evaluate a geometric ladder of prefix sizes; keep the largest
        feasible. Replaces the reference's sequential binary search
        (multinodeconsolidation.go:87-113).

        On a solver with batched-replan support (TPUSolver), the whole
        ladder is screened in ONE vmapped device dispatch over a shared
        union encode (solver/replan.py). A conclusive 0-new-machine winner
        becomes the DELETE command directly (validate_after_ttl re-simulates
        through the exact path before execution; a validation rejection
        flips the next ladder back to exact per-rung confirmation); REPLACE
        winners are always confirmed through the exact solve path, stepping
        down on disagreement. Without batched-replan support each rung is a
        full solve (host fallback)."""
        if len(candidates) < 2:
            return Command(action=ACTION_DO_NOTHING)
        n = len(candidates)
        sizes = sorted(
            {
                max(2, min(n, round(n ** (i / (self.LADDER_POINTS - 1)))))
                for i in range(self.LADDER_POINTS)
            }
        ) if n > 2 else [2]

        if getattr(self.provisioning.solver, "supports_batched_replan", False):
            return self._ladder_batched(candidates, sizes)
        best = Command(action=ACTION_DO_NOTHING)
        for size in sizes:
            cmd = self._evaluate_prefix(candidates, size)
            if cmd.action in (ACTION_REPLACE, ACTION_DELETE):
                best = cmd
            else:
                break  # larger prefixes are monotonically harder
        return best

    def _evaluate_prefix(self, candidates: List[CandidateNode], size: int) -> Command:
        """Exact evaluation of one prefix: full solve + price/same-type
        rules."""
        prefix = candidates[:size]
        cmd = self.compute_consolidation(prefix)
        if cmd.action == ACTION_REPLACE:
            cmd.replacement_machines[0].instance_type_options = self._filter_out_same_type(
                cmd.replacement_machines[0], prefix
            )
            if not cmd.replacement_machines[0].instance_type_options:
                cmd = Command(action=ACTION_DO_NOTHING)
        return cmd

    def _ladder_batched(self, candidates: List[CandidateNode],
                        sizes: List[int]) -> Command:
        """One vmapped screen over all rungs; conclusive 0-new-machine
        winners short-circuit to DELETE, REPLACE winners get exact
        confirmation (price and same-type rules live there), stepping down
        on disagreement. See first_n_consolidation_ladder for the
        validation backstop on the delete shortcut."""
        from karpenter_core_tpu.solver.replan import batched_ladder_screen

        confirm_deletes = getattr(self, "_confirm_deletes_once", False)
        try:
            screens = batched_ladder_screen(
                self.kube_client, self.cluster, self.provisioning, candidates,
                sizes, max_nodes=getattr(
                    self.provisioning.solver, "max_nodes", 1024
                ),
            )
        except CandidateNodeDeletingError:
            # transient (a candidate is mid-delete): keep the one-shot flag
            # so the NEXT successful ladder still runs exact confirmation
            return Command(action=ACTION_DO_NOTHING)
        self._confirm_deletes_once = False
        feasible = []
        blocked = []
        by_size = {}
        for screen in screens:
            if screen.all_scheduled and screen.conclusive and screen.n_new_machines <= 1:
                feasible.append(screen.size)
                by_size[screen.size] = screen
            else:
                blocked = [s.size for s in screens[len(feasible):]]
                break  # larger prefixes are monotonically harder
        for size in reversed(feasible):
            # A conclusive 0-new-machine rung IS the delete decision: the
            # screen ran the same round-0 kernel the exact path would (the
            # delete branch of consolidation.go:180-264 checks only "all
            # scheduled, zero replacements" — price/spot/same-type rules
            # exist only for REPLACE), relaxation could only make pods MORE
            # schedulable, and validate_after_ttl re-simulates through the
            # exact path before any node is touched. Skipping the
            # confirming solve here halves the replan's critical path.
            # confirm_deletes (set after a validation rejection of a
            # screen-sourced delete) routes this rung through the exact
            # path instead, restoring the step-down on disagreement.
            if by_size[size].n_new_machines == 0 and not confirm_deletes:
                return Command(
                    nodes_to_remove=[c.node for c in candidates[:size]],
                    action=ACTION_DELETE,
                    from_screen=True,
                )
            cmd = self._evaluate_prefix(candidates, size)
            if cmd.action in (ACTION_REPLACE, ACTION_DELETE):
                return cmd
        # The screen is the round-0 kernel only — no preference relaxation
        # (scheduler.go:114-123 relaxes until exhaustion). A negative screen
        # is therefore inconclusive when any involved pod still carries a
        # relaxable soft constraint; confirm those rungs through the exact
        # (relaxing) path before concluding nothing consolidates.
        if blocked and self._any_relaxable(candidates[: blocked[-1]]):
            best = Command(action=ACTION_DO_NOTHING)
            for size in blocked:
                cmd = self._evaluate_prefix(candidates, size)
                if cmd.action in (ACTION_REPLACE, ACTION_DELETE):
                    best = cmd
                else:
                    break
            return best
        return Command(action=ACTION_DO_NOTHING)

    def _any_relaxable(self, candidates: List[CandidateNode]) -> bool:
        from karpenter_core_tpu.controllers.provisioning.scheduling.preferences import (
            Preferences,
        )

        prefs = Preferences()
        pods = [p for c in candidates for p in c.pods]
        pods += list(self.provisioning.get_pending_pods())
        return any(prefs.is_relaxable(p) for p in pods)

    def _filter_out_same_type(self, replacement, consolidated: List[CandidateNode]):
        """multinodeconsolidation.go:133-166: prevent replacing with the same
        instance type unless strictly cheaper than the cheapest existing use
        of that type."""
        existing_types = set()
        prices_by_type = {}
        for c in consolidated:
            existing_types.add(c.instance_type.name)
            offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
            if offering is not None:
                prices_by_type[c.instance_type.name] = min(
                    prices_by_type.get(c.instance_type.name, math.inf), offering.price
                )
        max_price = math.inf
        for it in replacement.instance_type_options:
            if it.name in existing_types:
                max_price = min(max_price, prices_by_type.get(it.name, math.inf))
        return filter_by_price(
            replacement.instance_type_options, replacement.requirements, max_price
        )


class SingleNodeConsolidation(Consolidation):
    """singlenodeconsolidation.go:44-86."""

    def __str__(self) -> str:
        return "consolidation"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if self.cluster.consolidated():
            return Command(action=ACTION_DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        failed_validation = False
        for candidate in candidates:
            cmd = self.compute_consolidation([candidate])
            if cmd.action in (ACTION_DO_NOTHING, ACTION_RETRY):
                continue
            if not self.validate_after_ttl(cmd):
                failed_validation = True
                continue
            return cmd
        if failed_validation:
            return Command(action=ACTION_RETRY)
        return Command(action=ACTION_DO_NOTHING)
