"""Metrics scrapers: node/pod/provisioner gauges.

Mirrors reference pkg/controllers/metrics/{state/scraper/node.go, pod,
provisioner}: a 5s singleton scrape publishing node allocatable / pod
requests+limits / daemon overhead gauges labeled by well-known labels, the
per-pod phase gauge, and per-provisioner limit/usage gauges.
"""
from __future__ import annotations

import time

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.kube.objects import (
    LABEL_ARCH_STABLE,
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_OS_STABLE,
    LABEL_TOPOLOGY_REGION,
    LABEL_TOPOLOGY_ZONE,
)
from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY

SCRAPE_PERIOD = 5.0

_WELL_KNOWN_GAUGE_LABELS = {
    "zone": LABEL_TOPOLOGY_ZONE,
    "region": LABEL_TOPOLOGY_REGION,
    "instance_type": LABEL_INSTANCE_TYPE_STABLE,
    "arch": LABEL_ARCH_STABLE,
    "os": LABEL_OS_STABLE,
    "capacity_type": api_labels.LABEL_CAPACITY_TYPE,
    "provisioner": api_labels.PROVISIONER_NAME_LABEL_KEY,
}


def _node_labels(state_node, resource_name: str):
    labels = {"node_name": state_node.name(), "resource_type": resource_name}
    node_labels = state_node.labels()
    for gauge_label, node_label in _WELL_KNOWN_GAUGE_LABELS.items():
        labels[gauge_label] = node_labels.get(node_label, "")
    return labels


class NodeMetricsController:
    """metrics/state/scraper/node.go:28-115."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.allocatable = REGISTRY.gauge(f"{NAMESPACE}_nodes_allocatable")
        self.pod_requests = REGISTRY.gauge(f"{NAMESPACE}_nodes_total_pod_requests")
        self.pod_limits = REGISTRY.gauge(f"{NAMESPACE}_nodes_total_pod_limits")
        self.daemon_requests = REGISTRY.gauge(f"{NAMESPACE}_nodes_total_daemon_requests")
        self.daemon_limits = REGISTRY.gauge(f"{NAMESPACE}_nodes_total_daemon_limits")
        self.overhead = REGISTRY.gauge(f"{NAMESPACE}_nodes_system_overhead")

    def reconcile(self) -> float:
        # build-then-swap: the old clear()-before-repopulate left a window
        # where a concurrent REGISTRY.expose() observed an empty/partial
        # scrape. Each gauge's new series set is built in full here and
        # swapped atomically under the gauge lock (Gauge.replace_all) —
        # a scrape sees the previous generation or the new one, never a
        # blank exposition mid-rebuild.
        series = {
            gauge: []
            for gauge in (
                self.allocatable, self.pod_requests, self.pod_limits,
                self.daemon_requests, self.daemon_limits, self.overhead,
            )
        }
        for state_node in self.cluster.nodes():
            for name, q in state_node.allocatable().items():
                series[self.allocatable].append((q, _node_labels(state_node, name)))
            for name, q in state_node.total_pod_requests().items():
                series[self.pod_requests].append((q, _node_labels(state_node, name)))
            for name, q in state_node.total_pod_limits().items():
                series[self.pod_limits].append((q, _node_labels(state_node, name)))
            for name, q in state_node.total_daemonset_requests().items():
                series[self.daemon_requests].append((q, _node_labels(state_node, name)))
            for name, q in state_node.total_daemonset_limits().items():
                series[self.daemon_limits].append((q, _node_labels(state_node, name)))
            capacity = state_node.capacity()
            allocatable = state_node.allocatable()
            for name, q in capacity.items():
                series[self.overhead].append(
                    (q - allocatable.get(name, 0.0), _node_labels(state_node, name))
                )
        for gauge, pairs in series.items():
            gauge.replace_all(pairs)
        return SCRAPE_PERIOD


class PodMetricsController:
    """metrics/pod/controller.go:118-163: cleanup-then-record — every event
    first drops the pod's previous gauge (so phase transitions don't leave
    stale series) and re-records unless the pod is gone."""

    def __init__(self, kube_client, clock=time.time):
        self.kube_client = kube_client
        self.clock = clock
        self.state = REGISTRY.gauge(f"{NAMESPACE}_pods_state")
        self.startup = REGISTRY.histogram(f"{NAMESPACE}_pods_startup_time_seconds")
        self._started = set()
        self._labels = {}  # (namespace, name) -> last recorded label set

    def reconcile(self, pod, deleted: bool = False) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        prev = self._labels.pop(key, None)
        if prev is not None:
            self.state.delete(prev)
        if deleted:
            self._started.discard(pod.metadata.uid)
            return
        labels = {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "phase": pod.status.phase,
            "node": pod.spec.node_name,
        }
        self.state.set(1.0, labels)
        self._labels[key] = labels
        if pod.status.phase == "Running" and pod.metadata.uid not in self._started:
            self._started.add(pod.metadata.uid)
            # observation guard: an unset/zero creationTimestamp would
            # record a multi-decade startup and negative clock skew a
            # negative one — both corrupt every percentile of the
            # histogram, so the observation is skipped (the pod still
            # counts as started: re-observing later would be worse)
            created = pod.metadata.creation_timestamp
            if created:
                elapsed = self.clock() - created
                if elapsed >= 0.0:
                    self.startup.observe(elapsed)


class ProvisionerMetricsController:
    """metrics/provisioner/controller.go:107-135: cleanup-then-record — the
    previous gauge set is dropped on every event so resource-type changes and
    provisioner deletion don't leave stale series."""

    def __init__(self, kube_client):
        self.kube_client = kube_client
        self.limit = REGISTRY.gauge(f"{NAMESPACE}_provisioner_limit")
        self.usage = REGISTRY.gauge(f"{NAMESPACE}_provisioner_usage")
        self.usage_pct = REGISTRY.gauge(f"{NAMESPACE}_provisioner_usage_pct")
        self._labels = {}  # provisioner name -> [(gauge, labels), ...]

    def reconcile(self, provisioner, deleted: bool = False) -> None:
        for gauge, labels in self._labels.pop(provisioner.name, []):
            gauge.delete(labels)
        if deleted:
            return
        recorded = []
        base = {"provisioner": provisioner.name}
        if provisioner.spec.limits is not None:
            for name, q in provisioner.spec.limits.resources.items():
                labels = {**base, "resource_type": name}
                self.limit.set(q, labels)
                recorded.append((self.limit, labels))
        for name, q in provisioner.status.resources.items():
            labels = {**base, "resource_type": name}
            self.usage.set(q, labels)
            recorded.append((self.usage, labels))
            if (
                provisioner.spec.limits is not None
                and provisioner.spec.limits.resources.get(name)
            ):
                self.usage_pct.set(
                    q / provisioner.spec.limits.resources[name] * 100.0, labels
                )
                recorded.append((self.usage_pct, labels))
        self._labels[provisioner.name] = recorded

    def prune(self, live_names) -> None:
        """Drop series for provisioners no longer in the cluster — the
        level-triggered analog of reconciling a NotFound key
        (controller.go:117-123)."""
        for name in set(self._labels) - set(live_names):
            for gauge, labels in self._labels.pop(name, []):
                gauge.delete(labels)
