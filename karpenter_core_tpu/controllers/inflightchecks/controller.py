"""Periodic node health reports as events.

Mirrors reference pkg/controllers/inflightchecks: FailedInit (>1h
uninitialized with why, failedinit.go:30-82), NodeShape (capacity <90% of
expected, nodeshape.go:26-76), Termination (stuck deletes blocked by PDBs or
do-not-evict, inflightchecks/termination.go:26-55), deduped via the recorder
(controller.go:83-110; 10-minute period).
"""
from __future__ import annotations

import time
from typing import List, Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.kube.objects import Node
from karpenter_core_tpu.utils import podutils

FAILED_INIT_TIMEOUT = 3600.0  # 1h (failedinit.go)
NODE_SHAPE_RATIO = 0.9  # nodeshape.go
PERIOD = 10 * 60.0


class InflightChecksController:
    def __init__(self, kube_client, cloud_provider, cluster, recorder, clock=time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.recorder = recorder
        self.clock = clock

    def reconcile(self, node: Node) -> Optional[float]:
        if not node.metadata.labels.get(api_labels.PROVISIONER_NAME_LABEL_KEY):
            return None
        messages: List[str] = []
        messages += self._failed_init(node)
        messages += self._node_shape(node)
        messages += self._termination(node)
        for message in messages:
            self.recorder.node_inflight_check(node, message)
        return PERIOD

    def _failed_init(self, node: Node) -> List[str]:
        if node.metadata.labels.get(api_labels.LABEL_NODE_INITIALIZED) == "true":
            return []
        age = self.clock() - node.metadata.creation_timestamp
        if age < FAILED_INIT_TIMEOUT:
            return []
        why = []
        if not node.ready():
            why.append("node not ready")
        state_node = self.cluster.node_for(node.metadata.name) if self.cluster else None
        if state_node is not None and state_node.machine is not None:
            startup = {(t.key, t.value, t.effect) for t in state_node.machine.spec.startup_taints}
            remaining = [t for t in node.spec.taints if (t.key, t.value, t.effect) in startup]
            if remaining:
                why.append(f"startup taints remain: {[t.key for t in remaining]}")
        return [f"Node has not initialized in over 1 hour ({'; '.join(why) or 'unknown cause'})"]

    def _node_shape(self, node: Node) -> List[str]:
        state_node = self.cluster.node_for(node.metadata.name) if self.cluster else None
        if state_node is None or not node.ready():
            return []
        expected = state_node.inflight_capacity or (
            state_node.machine.status.capacity if state_node.machine else {}
        )
        out = []
        for name, quantity in expected.items():
            actual = node.status.capacity.get(name, 0.0)
            if quantity and actual < NODE_SHAPE_RATIO * quantity:
                out.append(
                    f"expected {quantity:g} of resource {name}, but found {actual:g} "
                    f"({actual / quantity:.1%} of expected)"
                )
        return out

    def _termination(self, node: Node) -> List[str]:
        if node.metadata.deletion_timestamp is None:
            return []
        from karpenter_core_tpu.controllers.deprovisioning.core import PDBLimits

        # only pods that need rescheduling can block a drain
        # (utils/node/node.go:30-48 GetNodePods)
        pods = [
            p
            for p in self.kube_client.list(
                "Pod", field_filter=lambda p: p.spec.node_name == node.metadata.name
            )
            if not (
                podutils.is_owned_by_node(p)
                or podutils.is_owned_by_daemonset(p)
                or podutils.is_terminal(p)
                or podutils.is_terminating(p)
            )
        ]
        messages = []
        # PDB blockers first — the common stuck-drain cause
        # (inflightchecks/termination.go:40-50)
        pdb, ok = PDBLimits(self.kube_client).can_evict_pods(pods)
        if not ok:
            messages.append(f"Can't drain node, PDB {pdb} is blocking evictions")
        blockers = []
        for pod in pods:
            if podutils.has_do_not_evict(pod):
                blockers.append(
                    f"pod {pod.metadata.namespace}/{pod.metadata.name} has do-not-evict"
                )
        if blockers:
            messages.append(f"Can't drain node, {'; '.join(blockers)}")
        return messages
