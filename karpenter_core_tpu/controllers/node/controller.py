"""Node hygiene controller: initialization labeling, emptiness timestamps,
finalizer/owner-ref, drift detection.

Mirrors reference pkg/controllers/node/{controller,initialization,emptiness,
finalizer,drift}.go.
"""
from __future__ import annotations

import time
from typing import Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.settings import current as current_settings
from karpenter_core_tpu.kube.objects import Node
from karpenter_core_tpu.utils import podutils


class NodeController:
    """node/controller.go:60-130: only acts on nodes owned by a
    provisioner."""

    DRIFT_REQUEUE = 5 * 60.0

    def __init__(self, kube_client, cloud_provider, cluster, clock=time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.clock = clock

    def reconcile(self, node: Node) -> Optional[float]:
        provisioner_name = node.metadata.labels.get(api_labels.PROVISIONER_NAME_LABEL_KEY)
        if not provisioner_name or node.metadata.deletion_timestamp is not None:
            return None
        provisioner = self.kube_client.get("Provisioner", "", provisioner_name)
        if provisioner is None:
            return None
        changed = False
        changed |= self._initialization(node)
        changed |= self._emptiness(node, provisioner)
        changed |= self._finalizer(node)
        requeue = self._drift(node)
        if changed:
            self.kube_client.apply(node)
            self.cluster.update_node(node)
        return requeue

    def _initialization(self, node: Node) -> bool:
        """node/initialization.go:39-70: label initialized once ready with
        inflight capacity resolved (nodes w/o a Machine record)."""
        if node.metadata.labels.get(api_labels.LABEL_NODE_INITIALIZED) == "true":
            return False
        if not node.ready():
            return False
        state_node = self.cluster.node_for(node.metadata.name)
        if state_node is not None and state_node.machine is not None:
            return False  # the machine controller owns initialization
        node.metadata.labels[api_labels.LABEL_NODE_INITIALIZED] = "true"
        return True

    def _emptiness(self, node: Node, provisioner) -> bool:
        """node/emptiness.go:44-90: write/remove the emptiness timestamp."""
        if provisioner.spec.ttl_seconds_after_empty is None:
            return False
        if node.metadata.labels.get(api_labels.LABEL_NODE_INITIALIZED) != "true":
            return False
        pods = self.kube_client.list(
            "Pod", field_filter=lambda p: p.spec.node_name == node.metadata.name
        )
        empty = not any(
            not podutils.is_terminal(p) and not podutils.is_owned_by_daemonset(p)
            for p in pods
        )
        key = api_labels.EMPTINESS_TIMESTAMP_ANNOTATION_KEY
        has_ts = key in node.metadata.annotations
        if empty and not has_ts:
            node.metadata.annotations[key] = str(self.clock())
            return True
        if not empty and has_ts:
            del node.metadata.annotations[key]
            return True
        return False

    def _finalizer(self, node: Node) -> bool:
        """node/finalizer.go:36-50."""
        if api_labels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(api_labels.TERMINATION_FINALIZER)
            return True
        return False

    def _drift(self, node: Node) -> Optional[float]:
        """node/drift.go:38-55: feature-gated annotation via
        cloudProvider.IsMachineDrifted, 5-minute requeue."""
        if not current_settings().drift_enabled:
            return None
        key = api_labels.VOLUNTARY_DISRUPTION_ANNOTATION_KEY
        if node.metadata.annotations.get(key) == api_labels.VOLUNTARY_DISRUPTION_DRIFTED_VALUE:
            return None
        machine_name = node.metadata.labels.get(api_labels.MACHINE_NAME_LABEL_KEY)
        machine = self.kube_client.get("Machine", "", machine_name) if machine_name else None
        if machine is None:
            from karpenter_core_tpu.api.machine import Machine as MachineCR

            machine = MachineCR()
            machine.metadata.name = node.metadata.name
            machine.status.provider_id = node.spec.provider_id
        try:
            drifted = self.cloud_provider.is_machine_drifted(machine)
        except Exception:
            return self.DRIFT_REQUEUE
        if drifted:
            node.metadata.annotations[key] = api_labels.VOLUNTARY_DISRUPTION_DRIFTED_VALUE
            self.kube_client.apply(node)
            self.cluster.update_node(node)
        return self.DRIFT_REQUEUE
