"""Terminator: cordon -> drain -> terminate, plus the async eviction queue.

Mirrors reference pkg/controllers/machine/terminator/{terminator,eviction}.go:
Cordon taints the node unschedulable; Drain evicts evictable pods (do-not-evict
blocks with an error; critical pods drain last); TerminateNode deletes the
cloud instance and removes the finalizer. The EvictionQueue is a rate-limited
worker with set-dedupe calling the eviction API; PDB 429s requeue with
backoff.
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import List, Optional, Set

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.kube.objects import (
    NamespacedName,
    Node,
    Pod,
    TAINT_NODE_UNSCHEDULABLE,
    Taint,
    object_key,
)
from karpenter_core_tpu.utils import podutils


class NodeDrainError(Exception):
    """Drain not finished yet; requeue (terminator.go NodeDrainError)."""


class PDBBlockedError(Exception):
    """Eviction blocked by a PodDisruptionBudget (HTTP 429 analog)."""


class EvictionQueue:
    """eviction.go:58-131: rate-limited workqueue with set dedupe.

    Requeue-with-backoff is a DELAY HEAP drained by the single worker
    thread (the reference's rate-limited workqueue shape): a PDB-blocked
    pod is pushed back with a ready-at time instead of spawning a
    threading.Timer per retry — under a large blocked drain the old
    timer-per-pod scheme churned one thread per (pod x retry)."""

    def __init__(self, kube_client, recorder=None, pdb_checker=None):
        self.kube_client = kube_client
        self.recorder = recorder
        self.pdb_checker = pdb_checker  # fn(pod) -> bool allowed
        self._set: Set[NamespacedName] = set()
        self._heap: list = []  # (ready_at, seq, key, attempts)
        self._seq = 0
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, *pods: Pod) -> None:
        with self._cond:
            for pod in pods:
                key = object_key(pod)
                if key not in self._set:
                    self._set.add(key)
                    heapq.heappush(self._heap, (0.0, self._seq, key, 0))
                    self._seq += 1
            self._cond.notify()

    def _requeue(self, key: NamespacedName, attempts: int) -> None:
        """PDB 429 -> exponential backoff requeue (eviction.go:110-124)."""
        delay = min(0.1 * (2**attempts), 10.0)
        with self._cond:
            heapq.heappush(
                self._heap, (time.monotonic() + delay, self._seq, key, attempts + 1)
            )
            self._seq += 1
            self._cond.notify()

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="eviction-queue"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    def _pop_ready(self, timeout: float = 0.1):
        """Earliest ready item, waiting up to `timeout` for one to arrive
        or ripen. Returns (key, attempts) or None."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._stop.is_set():
                now = time.monotonic()
                if self._heap:
                    ready_at = self._heap[0][0]
                    if ready_at <= now:
                        _, _, key, attempts = heapq.heappop(self._heap)
                        return key, attempts
                    wait = min(ready_at, deadline) - now
                else:
                    wait = deadline - now
                if wait <= 0:
                    return None
                self._cond.wait(wait)
            return None

    def _run(self) -> None:
        while not self._stop.is_set():
            item = self._pop_ready()
            if item is None:
                continue
            key, attempts = item
            if self.evict(key):
                with self._cond:
                    self._set.discard(key)
            else:
                self._requeue(key, attempts)

    def evict(self, key: NamespacedName) -> bool:
        """One eviction API call (eviction.go:87-108). True on success or
        gone; False when PDB-blocked.

        Goes through the pods/eviction SUBRESOURCE when the client has one
        (both InMemoryKubeClient and ApiServerKubeClient do): the apiserver
        enforces PodDisruptionBudgets and answers 429
        (EvictionBlockedError -> requeue with backoff), so budget
        arbitration is server-side instead of a host check racing other PDB
        consumers (eviction.go:111-124). pdb_checker remains an optional
        EXTRA host-side gate for embedders with custom policies."""
        from karpenter_core_tpu.kube.client import EvictionBlockedError

        pod = self.kube_client.get("Pod", key.namespace, key.name)
        if pod is None:
            return True
        if self.pdb_checker is not None and not self.pdb_checker(pod):
            return False
        evict = getattr(self.kube_client, "evict", None)
        try:
            if evict is not None:
                evict(key.namespace, key.name)
            else:
                self.kube_client.delete("Pod", key.namespace, key.name)
        except EvictionBlockedError:
            return False  # server-enforced PDB 429
        except Exception:
            return True
        if self.recorder:
            self.recorder.evict_pod(pod)
        return True

    def drain(self) -> None:
        """Synchronously process everything queued (for tests/sync paths)."""
        while True:
            with self._cond:
                pending = list(self._set)
            if not pending:
                return
            progressed = False
            for key in pending:
                if self.evict(key):
                    with self._cond:
                        self._set.discard(key)
                    progressed = True
            if not progressed:
                return


class Terminator:
    """terminator.go:40-155."""

    def __init__(self, kube_client, cloud_provider, eviction_queue: EvictionQueue, clock=time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.eviction_queue = eviction_queue
        self.clock = clock

    def cordon(self, node: Node) -> None:
        """terminator.go:53-68: mark unschedulable."""
        if node.spec.unschedulable:
            return
        node.spec.unschedulable = True
        if not any(t.key == TAINT_NODE_UNSCHEDULABLE for t in node.spec.taints):
            node.spec.taints.append(
                Taint(key=TAINT_NODE_UNSCHEDULABLE, effect="NoSchedule")
            )
        self.kube_client.update(node)

    def drain(self, node: Node) -> None:
        """terminator.go:70-100: evict evictable pods; do-not-evict blocks;
        critical pods drain after the rest. Raises NodeDrainError until
        empty."""
        pods = self.kube_client.list(
            "Pod", field_filter=lambda p: p.spec.node_name == node.metadata.name
        )
        evictable: List[Pod] = []
        critical: List[Pod] = []
        for pod in pods:
            if podutils.is_owned_by_daemonset(pod) or podutils.is_owned_by_node(pod):
                continue
            if podutils.is_terminal(pod):
                continue
            if podutils.has_do_not_evict(pod) and pod.metadata.deletion_timestamp is None:
                raise NodeDrainError(
                    f"pod {pod.metadata.namespace}/{pod.metadata.name} has do-not-evict annotation"
                )
            if pod.spec.priority_class_name in ("system-cluster-critical", "system-node-critical"):
                critical.append(pod)
            else:
                evictable.append(pod)
        # drain critical pods last (terminator.go:131-155)
        batch = evictable if evictable else critical
        if batch:
            self.eviction_queue.add(*batch)
            raise NodeDrainError(f"{len(evictable) + len(critical)} pods are waiting to be evicted")

    def terminate_node(self, node: Node) -> None:
        """terminator.go:102-129: delete the instance, then drop the
        finalizer so the apiserver completes deletion."""
        state_machine = self.kube_client.get("Machine", "", node.metadata.name)
        from karpenter_core_tpu.api.machine import Machine as MachineCR
        from karpenter_core_tpu.cloudprovider.types import MachineNotFoundError

        machine = state_machine
        if machine is None:
            machine = MachineCR()
            machine.metadata.name = node.metadata.name
            machine.status.provider_id = node.spec.provider_id
        try:
            self.cloud_provider.delete(machine)
        except MachineNotFoundError:
            pass
        if api_labels.TERMINATION_FINALIZER in node.metadata.finalizers:
            node.metadata.finalizers.remove(api_labels.TERMINATION_FINALIZER)
            self.kube_client.finalize(node)
