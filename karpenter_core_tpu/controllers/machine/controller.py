"""Machine lifecycle controller: Launch -> Registration -> Initialization,
with Liveness TTL and a drain-then-delete finalizer.

Mirrors reference pkg/controllers/machine/{controller,launch,registration,
initialization,liveness}.go: Launch calls cloudProvider.Create for machines
with no ProviderID; Registration finds the node by providerID and syncs
labels/taints/startup-taints plus the termination finalizer; Initialization
flips MachineInitialized once the node is Ready, startup taints are gone, and
extended resources are registered; Liveness deletes machines that never
register within TTLAfterNotRegistered.
"""
from __future__ import annotations

import time
from typing import Optional

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.machine import (
    CONDITION_MACHINE_INITIALIZED,
    CONDITION_MACHINE_LAUNCHED,
    CONDITION_MACHINE_REGISTERED,
    Machine,
)
from karpenter_core_tpu.api.settings import current as current_settings
from karpenter_core_tpu.cloudprovider.types import MachineNotFoundError
from karpenter_core_tpu.controllers.machine.terminator import NodeDrainError, Terminator
from karpenter_core_tpu.kube.objects import Node
from karpenter_core_tpu.metrics.registry import MACHINES_CREATED, MACHINES_TERMINATED
from karpenter_core_tpu.scheduling import taints as taints_mod


class MachineController:
    """machine/controller.go:60-166 (50 parallel reconciles in the reference;
    concurrency belongs to the operator runtime here)."""

    def __init__(self, kube_client, cloud_provider, cluster, terminator: Terminator,
                 recorder=None, clock=time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.terminator = terminator
        self.recorder = recorder
        self.clock = clock

    def reconcile(self, machine: Machine) -> Optional[float]:
        """Returns an optional requeue-after in seconds."""
        if machine.metadata.deletion_timestamp is not None:
            return self.finalize(machine)
        requeue = None
        for step in (self.launch, self.registration, self.initialization, self.liveness):
            r = step(machine)
            if r == "deleted":
                return None
            if isinstance(r, (int, float)):
                requeue = min(requeue, r) if requeue is not None else r
        self._sync_ready(machine)
        # metadata/spec ride the plain PUT; conditions/providerID/capacity
        # live under the status SUBRESOURCE, which a plain PUT silently
        # drops — they must go through Status().Update (machine
        # controller.go status writes; CRD `subresources: {status: {}}`).
        # Rebase on apply's returned rv (the REST adapter does not mutate
        # the passed object) so the status PUT doesn't 409 every reconcile;
        # a machine deleted mid-reconcile is a clean no-op, not an error.
        from karpenter_core_tpu.kube.client import NotFoundError

        try:
            applied = self.kube_client.apply(machine)
            machine.metadata.resource_version = applied.metadata.resource_version
            self.kube_client.update_status(machine)
        except NotFoundError:
            return None  # deleted by a concurrent worker
        self.cluster.update_machine(machine)
        return requeue

    # -- sub-reconcilers ----------------------------------------------------

    def launch(self, machine: Machine):
        """launch.go:35-77."""
        if machine.status.provider_id:
            machine.set_condition(CONDITION_MACHINE_LAUNCHED, "True")
            return None
        try:
            created = self.cloud_provider.get(machine.name)
        except MachineNotFoundError:
            try:
                created = self.cloud_provider.create(machine)
                MACHINES_CREATED.inc()
            except Exception as e:
                machine.set_condition(
                    CONDITION_MACHINE_LAUNCHED, "False", "LaunchFailed", str(e)
                )
                return 10.0
        machine.status.provider_id = created.status.provider_id
        machine.status.capacity = dict(created.status.capacity)
        machine.status.allocatable = dict(created.status.allocatable)
        machine.metadata.labels.update(created.metadata.labels)
        machine.set_condition(CONDITION_MACHINE_LAUNCHED, "True")
        return None

    def registration(self, machine: Machine):
        """registration.go:38-98: find the node by providerID, sync
        labels/taints, add the termination finalizer."""
        if not machine.status.provider_id:
            return None
        node = self._node_for(machine)
        if node is None:
            machine.set_condition(
                CONDITION_MACHINE_REGISTERED, "False", "NodeNotFound", "node has not registered"
            )
            return None
        node.metadata.labels.update(machine.metadata.labels)
        node.metadata.labels[api_labels.MACHINE_NAME_LABEL_KEY] = machine.name
        node.spec.taints = taints_mod.merge(node.spec.taints, machine.spec.taints)
        if not machine.condition_true(CONDITION_MACHINE_REGISTERED):
            # startupTaints sync exactly ONCE, at first registration: once
            # the node agent removes them they must NOT reappear on later
            # reconciles (registration.go:38-98; suite_test.go:363-409)
            node.spec.taints = taints_mod.merge(
                node.spec.taints, machine.spec.startup_taints
            )
        if api_labels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(api_labels.TERMINATION_FINALIZER)
        self.kube_client.apply(node)
        self.cluster.update_node(node)
        machine.set_condition(CONDITION_MACHINE_REGISTERED, "True")
        return None

    def initialization(self, machine: Machine):
        """initialization.go:42-90: NodeReady ∧ startup taints gone ∧
        extended resources registered -> MachineInitialized + node label."""
        if not machine.condition_true(CONDITION_MACHINE_REGISTERED):
            return None
        node = self._node_for(machine)
        if node is None:
            return None
        if not node.ready():
            machine.set_condition(
                CONDITION_MACHINE_INITIALIZED, "False", "NodeNotReady", "node not ready"
            )
            return None
        startup_keys = {(t.key, t.value, t.effect) for t in machine.spec.startup_taints}
        if any((t.key, t.value, t.effect) in startup_keys for t in node.spec.taints):
            machine.set_condition(
                CONDITION_MACHINE_INITIALIZED, "False", "StartupTaintsExist", "startup taints remain"
            )
            return None
        for name, quantity in machine.status.allocatable.items():
            if quantity and not node.status.allocatable.get(name):
                machine.set_condition(
                    CONDITION_MACHINE_INITIALIZED,
                    "False",
                    "ResourceNotRegistered",
                    f"extended resource {name} not registered",
                )
                return None
        node.metadata.labels[api_labels.LABEL_NODE_INITIALIZED] = "true"
        self.kube_client.apply(node)
        self.cluster.update_node(node)
        machine.set_condition(CONDITION_MACHINE_INITIALIZED, "True")
        return None

    def liveness(self, machine: Machine):
        """liveness.go:33-60: unregistered past TTL -> delete the machine."""
        if machine.condition_true(CONDITION_MACHINE_REGISTERED):
            return None
        ttl = current_settings().ttl_after_not_registered
        if ttl is None:
            return None  # reaper disabled (settings.go TTLAfterNotRegistered)
        age = self.clock() - machine.metadata.creation_timestamp
        if age < ttl:
            return ttl - age
        try:
            self.kube_client.delete("Machine", "", machine.name)
        except Exception:
            pass
        return "deleted"

    def finalize(self, machine: Machine):
        """controller.go:122-146: drain the node, delete the instance, drop
        the finalizer."""
        node = self._node_for(machine)
        if node is not None:
            self.terminator.cordon(node)
            try:
                self.terminator.drain(node)
            except NodeDrainError:
                return 1.0
        try:
            self.cloud_provider.delete(machine)
            MACHINES_TERMINATED.inc()
        except MachineNotFoundError:
            pass
        if node is not None and api_labels.TERMINATION_FINALIZER in node.metadata.finalizers:
            node.metadata.finalizers.remove(api_labels.TERMINATION_FINALIZER)
            self.kube_client.finalize(node)
        if api_labels.TERMINATION_FINALIZER in machine.metadata.finalizers:
            machine.metadata.finalizers.remove(api_labels.TERMINATION_FINALIZER)
            self.kube_client.finalize(machine)
        self.cluster.delete_machine(machine.name)
        return None

    # -- helpers ------------------------------------------------------------

    def _node_for(self, machine: Machine) -> Optional[Node]:
        for node in self.kube_client.list("Node"):
            if node.spec.provider_id == machine.status.provider_id:
                return node
        return None

    def _sync_ready(self, machine: Machine) -> None:
        ready = (
            machine.condition_true(CONDITION_MACHINE_LAUNCHED)
            and machine.condition_true(CONDITION_MACHINE_REGISTERED)
            and machine.condition_true(CONDITION_MACHINE_INITIALIZED)
        )
        machine.set_condition("Ready", "True" if ready else "False")
