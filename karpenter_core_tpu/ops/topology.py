"""Topology as device-side domain-count tensors.

Lowers the host Topology (controllers/provisioning/scheduling/topology.py,
mirroring reference topology.go/topologygroup.go) onto arrays the packing
kernel updates in-place:

  counts[G, V]      per-group occupancy per domain (flat value axis) —
                    zone/region/custom-key groups
  hcounts[G, N]     hostname-key groups count per SLOT: a machine slot is
                    identical to its (placeholder) hostname domain
                    (machine.go:44-48 registers one fresh hostname per
                    machine), so slot identity replaces dictionary values and
                    the value axis stays small at 50k pods
  domain_mask[G, V] which flat values are registered domains of the group
  owner[G, P]       pod carries the constraint (direct groups)
  sel[G, P]         group's selector matches the pod

Per-(pod, slot) viability and the committed narrowing follow
topologygroup.go:155-243; Record follows topology.go:120-143 including the
anti-affinity "block out all possible domains" rule and the
Requirement.Values() complement quirk.

Known approximation: hostname domains of nodes NOT in the candidate set
(unowned nodes) are invisible to hostname-affinity seeding — such domains are
never placeable anyway, and hostname spread's min-count is pinned to 0 by the
reference (topologygroup.go:186-188), so placement decisions match.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

TOPO_SPREAD = 0
TOPO_AFFINITY = 1
TOPO_ANTI = 2


@dataclass
class TopoGroupMeta:
    """Static (trace-time) description of one group."""

    gtype: int
    seg: Tuple[int, int]
    key_k: int  # key index (for the complement flag of merged requirements)
    max_skew: int
    is_hostname: bool
    is_inverse: bool
    filter_term_rows: List[int]  # rows into the filter-term ReqSet arrays


@dataclass
class TopoArrays:
    """Dynamic per-solve arrays."""

    counts0: np.ndarray  # [G, V] float32 (value-key groups)
    hcounts0: np.ndarray  # [G, N] float32 (hostname groups, per slot)
    domain_mask0: np.ndarray  # [G, V] bool
    owner: np.ndarray  # [G, P] bool
    sel: np.ndarray  # [G, P] bool
    # node-filter terms as a flat ReqSet batch
    term_allow: np.ndarray  # [GT, V]
    term_out: np.ndarray  # [GT, K]
    term_defined: np.ndarray  # [GT, K]
    term_escape: np.ndarray  # [GT, K]


@dataclass
class TopoMeta:
    groups: List[TopoGroupMeta] = field(default_factory=list)


def encode_topology(
    host_topology,
    pods_sorted,
    dictionary,
    n_slots: int,
    exist_hostnames: List[str],
    uidx=None,
    uniq_pods=None,
) -> Tuple[Optional[TopoMeta], Optional[TopoArrays]]:
    """Lower a host Topology (already seeded with cluster counts) to arrays.
    exist_hostnames[e] maps existing slot e -> its hostname domain.
    Returns (None, None) when the batch has no topology constraints.

    When (uidx, uniq_pods) is given — uidx[i] = pod i's spec-equivalence
    class, uniq_pods[u] = that class's representative (a member of the
    batch) — ownership and selection are evaluated once per class and
    gathered, turning the G x P Python loops into G x U."""
    from karpenter_core_tpu.kube.objects import LABEL_HOSTNAME
    from karpenter_core_tpu.solver.encode import encode_reqsets

    groups = list(host_topology.topologies.values()) + list(
        host_topology.inverse_topologies.values()
    )
    if not groups:
        return None, None

    P = len(pods_sorted)
    V = dictionary.V
    G = len(groups)
    per_class = uidx is not None and uniq_pods is not None
    if not per_class:
        uid_to_idx = {p.metadata.uid: i for i, p in enumerate(pods_sorted)}
    n_direct = len(host_topology.topologies)

    metas: List[TopoGroupMeta] = []
    counts0 = np.zeros((G, V), dtype=np.float32)
    hcounts0 = np.zeros((G, n_slots), dtype=np.float32)
    domain_mask0 = np.zeros((G, V), dtype=bool)
    U = len(uniq_pods) if per_class else P
    owner_u = np.zeros((G, U), dtype=bool)
    sel_u = np.zeros((G, U), dtype=bool)
    term_reqs = []

    type_map = {
        "topology spread": TOPO_SPREAD,
        "pod affinity": TOPO_AFFINITY,
        "pod anti-affinity": TOPO_ANTI,
    }
    for g, tg in enumerate(groups):
        is_hostname = tg.key == LABEL_HOSTNAME
        seg = dictionary.segment(tg.key) if tg.key in dictionary.key_index else (0, 0)
        rows = []
        for term in tg.node_filter.terms:
            rows.append(len(term_reqs))
            term_reqs.append(term)
        metas.append(
            TopoGroupMeta(
                gtype=type_map[tg.type],
                seg=seg,
                key_k=dictionary.key_index.get(tg.key, 0),
                max_skew=int(tg.max_skew),
                is_hostname=is_hostname,
                is_inverse=(g >= n_direct),
                filter_term_rows=rows,
            )
        )
        if is_hostname:
            for e, hostname in enumerate(exist_hostnames):
                hcounts0[g, e] = tg.domains.get(hostname, 0)
        else:
            for domain, count in tg.domains.items():
                fi = dictionary.flat_index(tg.key, domain)
                if fi is None:
                    continue
                domain_mask0[g, fi] = True
                counts0[g, fi] = count
        if per_class:
            for u, rep in enumerate(uniq_pods):
                owner_u[g, u] = tg.is_owned_by(rep.metadata.uid)
                sel_u[g, u] = tg._selects(rep)
        else:
            for uid in tg.owners:
                if uid in uid_to_idx:
                    owner_u[g, uid_to_idx[uid]] = True
            for i, pod in enumerate(pods_sorted):
                sel_u[g, i] = tg._selects(pod)

    if per_class:
        owner = owner_u[:, uidx]
        sel = sel_u[:, uidx]
    else:
        owner, sel = owner_u, sel_u
    encoded_terms = encode_reqsets(term_reqs, dictionary)
    meta = TopoMeta(groups=metas)
    arrays = TopoArrays(
        counts0=counts0,
        hcounts0=hcounts0,
        domain_mask0=domain_mask0,
        owner=owner,
        sel=sel,
        term_allow=encoded_terms.allow,
        term_out=encoded_terms.out,
        term_defined=encoded_terms.defined,
        term_escape=encoded_terms.escape,
    )
    return meta, arrays


# ---------------------------------------------------------------------------
# device-side group evaluation (used inside the packing scan)


def _first_true_onehot(mask):
    """[..., S] bool -> onehot of the first True (all-False rows -> zeros)."""
    import jax.numpy as jnp

    idx = jnp.argmax(mask, axis=-1)
    oh = jnp.arange(mask.shape[-1]) == idx[..., None]
    return oh & mask.any(axis=-1, keepdims=True)


def topo_screen(meta: TopoMeta, tcounts, thost, tdoms, own, selp, pod_allow, slot_allow):
    """Batched viability over all slots: [N] bool.

    own/selp: [G] bool for THIS pod; pod_allow [V]; slot_allow [N, V].
    Follows topologygroup.go Get(): spread skew rule, affinity positive/seed
    domains, anti-affinity zero-count domains. Hostname groups evaluate on
    slot identity (thost [G, N])."""
    import jax.numpy as jnp

    N = slot_allow.shape[0]
    viable = jnp.ones(N, dtype=bool)
    for g, gm in enumerate(meta.groups):
        applies = selp[g] if gm.is_inverse else own[g]
        if gm.is_hostname:
            hc = thost[g]  # [N]
            if gm.gtype == TOPO_SPREAD:
                c = hc + selp[g].astype(jnp.float32)
                g_viable = c - 0.0 <= gm.max_skew  # hostname min pinned to 0
            elif gm.gtype == TOPO_AFFINITY:
                has_pos = (hc > 0.5).any()
                g_viable = jnp.where(has_pos, hc > 0.5, jnp.broadcast_to(selp[g], hc.shape))
            else:
                g_viable = hc < 0.5
        else:
            lo, hi = gm.seg
            doms = tdoms[g, lo:hi]
            cnt = tcounts[g, lo:hi]
            pod_dom = pod_allow[lo:hi]
            sallow = slot_allow[:, lo:hi]
            if gm.gtype == TOPO_SPREAD:
                # membership-only: the packing loop's water-fill allocation
                # decides which domain each commit targets (per-pod skew is
                # enforced there); screening on the instantaneous skew rule
                # would wrongly exclude slots whose domain the allocation
                # will reach at a later fill level
                g_viable = ((pod_dom & doms)[None, :] & sallow).any(axis=-1)
            elif gm.gtype == TOPO_AFFINITY:
                pos = pod_dom & doms & (cnt > 0.5)
                has_pos = pos.any()
                seed1 = _first_true_onehot(pod_dom[None, :] & doms[None, :] & sallow)
                seed2 = _first_true_onehot((pod_dom & doms)[None, :])
                seeded = seed1 | seed2
                opts = jnp.where(has_pos, pos[None, :], jnp.where(selp[g], seeded, False))
                g_viable = (opts & sallow).any(axis=-1)
            else:  # TOPO_ANTI
                opts = pod_dom & doms & (cnt < 0.5)
                g_viable = (opts[None, :] & sallow).any(axis=-1)
        viable &= ~applies | g_viable
    return viable


def topo_narrow_single(meta: TopoMeta, tcounts, thost, tdoms, own, selp,
                       pod_allow, slot_allow_row, slot_n, n_keys: int,
                       spread_force=None):
    """(viable, narrow[V], applied_keys[K], k_cap) for ONE candidate slot.
    The returned applied_keys mark keys that become DEFINED concrete In-sets
    on the merged requirements (AddRequirements adds them,
    topology.go:149-167). Hostname groups evaluate on the slot's identity and
    narrow nothing.

    Value-key spread narrowing is driven by spread_force [V] — the packing
    loop's water-fill domain choice for this iteration (the bulk analog of
    the per-pod argmin-count rule, topologygroup.go:155-182); the slot is
    viable iff it allows the forced domain. A None spread_force admits every
    registered domain (the caller enforces the skew/allocation bound).

    k_cap (int32) bounds how many IDENTICAL replicas of this pod the slot can
    take while the final state still satisfies the constraint — the skew
    headroom of owned hostname-spread groups (min-count pinned to 0,
    topologygroup.go:186-188). Anti-affinity classes are expanded to count=1
    items at encode, so they never consume k_cap > 1."""
    import jax.numpy as jnp

    V = slot_allow_row.shape[0]
    viable = jnp.bool_(True)
    narrow = jnp.ones(V, dtype=bool)
    applied_keys = jnp.zeros(n_keys, dtype=bool)
    k_cap = jnp.int32(2**30)
    for g, gm in enumerate(meta.groups):
        applies = selp[g] if gm.is_inverse else own[g]
        if gm.is_hostname:
            hc = thost[g, slot_n]
            if gm.gtype == TOPO_SPREAD:
                g_viable = hc + selp[g].astype(jnp.float32) <= gm.max_skew
                headroom = jnp.maximum(
                    jnp.float32(gm.max_skew) - hc, 0.0
                ).astype(jnp.int32)
                k_cap = jnp.where(
                    applies & selp[g], jnp.minimum(k_cap, headroom), k_cap
                )
            elif gm.gtype == TOPO_AFFINITY:
                has_pos = (thost[g] > 0.5).any()
                g_viable = jnp.where(has_pos, hc > 0.5, selp[g])
            else:
                g_viable = hc < 0.5
                # only the DIRECT group 1-caps replicas: an owner's replicas
                # repel each other (owners kept bulk match their own
                # selector; non-matching owners are expanded at encode).
                # Followers merely SELECTED by the inverse group do not
                # record into the inverse plane (only owners do) and may
                # stack on a non-owner slot, as in the reference.
                if not gm.is_inverse:
                    k_cap = jnp.where(applies, jnp.minimum(k_cap, 1), k_cap)
            viable &= ~applies | g_viable
            continue
        lo, hi = gm.seg
        doms = tdoms[g, lo:hi]
        cnt = tcounts[g, lo:hi]
        pod_dom = pod_allow[lo:hi]
        sallow = slot_allow_row[lo:hi]
        if gm.gtype == TOPO_SPREAD:
            # domain choice is the packing loop's water-fill plan; absent a
            # plan every registered domain is admissible
            sf = spread_force[lo:hi] if spread_force is not None else doms
            g_narrow = sf & doms
            g_viable = (g_narrow & sallow).any()
        elif gm.gtype == TOPO_AFFINITY:
            pos = pod_dom & doms & (cnt > 0.5)
            has_pos = pos.any()
            seed1 = _first_true_onehot((pod_dom & doms & sallow)[None, :])[0]
            seed2 = _first_true_onehot((pod_dom & doms)[None, :])[0]
            seeded = seed1 | seed2
            g_narrow = jnp.where(has_pos, pos, jnp.where(selp[g], seeded, False))
            g_viable = (g_narrow & sallow).any()
        else:
            g_narrow = pod_dom & doms & (cnt < 0.5)
            g_viable = (g_narrow & sallow).any()
            k_cap = jnp.where(applies, jnp.minimum(k_cap, 1), k_cap)
        viable &= ~applies | g_viable
        seg_new = jnp.where(applies, narrow[lo:hi] & g_narrow, narrow[lo:hi])
        narrow = narrow.at[lo:hi].set(seg_new)
        applied_keys = applied_keys.at[gm.key_k].max(applies)
    return viable, narrow, applied_keys, k_cap


def topo_bulk_item_ok(meta: TopoMeta, own, selp):
    """Scalar bool: may this item take the bulk existing-fill fast path?

    The bulk path fills MANY existing slots in one iteration with per-slot
    singleton-domain counting, so it excludes items whose placement records
    non-singleton deltas or requires per-slot sequencing:
      - anti-affinity (owner or selected, direct or inverse-owner): each
        placement records vals over all possible domains (topo_record) and
        changes the next slot's viability;
      - hostname pod-affinity owners: replicas must co-locate on one host;
      - groups with node-filter terms: nf_ok is per merged slot row, which
        the bulk path does not evaluate.
    """
    import jax.numpy as jnp

    ok = jnp.bool_(True)
    for g, gm in enumerate(meta.groups):
        has_terms = len(gm.filter_term_rows) > 0
        if gm.is_inverse:
            ok &= ~own[g]
            if has_terms:
                ok &= ~selp[g]
            continue
        if gm.gtype == TOPO_ANTI:
            ok &= ~(own[g] | selp[g])
        elif gm.gtype == TOPO_AFFINITY and gm.is_hostname:
            ok &= ~own[g]
        if has_terms:
            ok &= ~(own[g] | selp[g])
    return ok


def topo_mach_bulk_item_ok(meta: TopoMeta, own, selp):
    """Scalar bool: may this item take the FULL-AXIS (machine-region) bulk
    fill? Superset of topo_bulk_item_ok's admission that additionally allows
    hostname anti-affinity involvement — a hostname group's domain IS the
    slot, so each placement only changes its own slot's viability and the
    per-slot take computed from pre-iteration counts stays exact:

      - hostname direct anti (own and/or selected): screened per slot on
        thost==0, capped at 1 replica/slot by topo_bulk_narrow; recording is
        thost[g, slot] += take (slot-local). Owner classes that do NOT match
        their own selector are expanded to count=1 items at encode (their
        replicas may legally co-locate), so own => selp here and the 1-cap
        is exact.
      - hostname inverse anti: the selected side screens on the inverse
        plane (slot-local); the owner side records into it (slot-local).
        own of an inverse group implies own of the paired direct group, so
        self-matching owners are already 1-capped by the direct group.

    Everything with cross-slot effects keeps the exclusions of
    topo_bulk_item_ok: value-key anti (a placement in domain d kills every
    slot of d), hostname-affinity owners (replicas must co-locate on one
    seeded host), and node-filter terms (nf_ok is per merged slot row)."""
    import jax.numpy as jnp

    ok = jnp.bool_(True)
    for g, gm in enumerate(meta.groups):
        has_terms = len(gm.filter_term_rows) > 0
        if has_terms:
            ok &= ~(own[g] | selp[g])
            continue
        if gm.is_inverse:
            if not gm.is_hostname:
                ok &= ~own[g]
            continue
        if gm.gtype == TOPO_ANTI and not gm.is_hostname:
            ok &= ~(own[g] | selp[g])
        elif gm.gtype == TOPO_AFFINITY and gm.is_hostname:
            ok &= ~own[g]
    return ok


def topo_bulk_need_seed(meta: TopoMeta, tcounts, tdoms, own, pod_allow):
    """Scalar bool: an owned value-key affinity group has NO positive domain
    yet — the first replica must seed one via the single-slot path before
    the bulk path can fill against positive domains."""
    import jax.numpy as jnp

    need = jnp.bool_(False)
    for g, gm in enumerate(meta.groups):
        if gm.is_inverse or gm.is_hostname or gm.gtype != TOPO_AFFINITY:
            continue
        lo, hi = gm.seg
        has_pos = (
            pod_allow[lo:hi] & tdoms[g, lo:hi] & (tcounts[g, lo:hi] > 0.5)
        ).any()
        need |= own[g] & ~has_pos
    return need


def topo_bulk_narrow(meta: TopoMeta, tcounts, thost, tdoms, own, selp,
                     pod_allow, n_keys: int, spread_force=None):
    """(narrow[V], applied_keys[K], k_cap[N]) for the bulk existing fill.

    Unlike topo_narrow_single the narrowing row is SLOT-INDEPENDENT (domain
    choice depends only on counts/registered domains/the water-fill force),
    so one row merges into every filled slot; per-slot admission is the
    caller's viability screen ∧ (slot allows the narrowed domains). k_cap[N]
    is the per-slot replica headroom of owned hostname-spread groups."""
    import jax.numpy as jnp

    V = pod_allow.shape[0]
    N = thost.shape[1] if thost.ndim == 2 else 0
    narrow = jnp.ones(V, dtype=bool)
    applied = jnp.zeros(n_keys, dtype=bool)
    k_cap = jnp.full(N, jnp.int32(2**30), dtype=jnp.int32)
    for g, gm in enumerate(meta.groups):
        if gm.is_inverse:
            continue
        if gm.is_hostname:
            if gm.gtype == TOPO_SPREAD:
                headroom = jnp.maximum(
                    jnp.float32(gm.max_skew) - thost[g], 0.0
                ).astype(jnp.int32)
                k_cap = jnp.where(
                    own[g] & selp[g], jnp.minimum(k_cap, headroom), k_cap
                )
            elif gm.gtype == TOPO_ANTI:
                # one replica per zero-count slot (the machine-region bulk
                # admits hostname anti; count>0 slots are screened out by
                # topo_screen, the 1-cap stops two replicas sharing a slot)
                k_cap = jnp.where(
                    own[g], jnp.minimum(k_cap, 1), k_cap
                )
            continue
        lo, hi = gm.seg
        doms = tdoms[g, lo:hi]
        if gm.gtype == TOPO_SPREAD:
            sf = spread_force[lo:hi] if spread_force is not None else doms
            g_narrow = sf & doms
        elif gm.gtype == TOPO_AFFINITY:
            g_narrow = pod_allow[lo:hi] & doms & (tcounts[g, lo:hi] > 0.5)
        else:
            continue
        seg_new = jnp.where(own[g], narrow[lo:hi] & g_narrow, narrow[lo:hi])
        narrow = narrow.at[lo:hi].set(seg_new)
        applied = applied.at[gm.key_k].max(own[g])
    return narrow, applied, k_cap


def topo_record_bulk(meta: TopoMeta, tcounts, thost, tdoms, own, selp,
                     m_allow_rows, m_out_rows, k_row):
    """Per-slot merged-row variant of topo_record for the bulk fills.

    Reachable for items topo_bulk_item_ok admits (existing-prefix fill) and
    items topo_mach_bulk_item_ok admits (machine-region fill — additionally
    hostname anti own/selp and hostname-inverse own, all of which record
    slot-locally through the thost lane below). Neither admits value-key
    anti involvement or filter terms, so value-key counting is the
    singleton rule evaluated per slot and nf_ok is vacuously true. k_row /
    m_allow_rows / m_out_rows may cover only a PREFIX of the slot axis;
    hostname counts update that prefix in place."""
    import jax.numpy as jnp

    k_row_f = k_row.astype(jnp.float32)
    touched = k_row > 0
    n_pre = k_row.shape[0]
    for g, gm in enumerate(meta.groups):
        if gm.is_hostname:
            rec = own[g] if gm.is_inverse else selp[g]
            thost = thost.at[g, :n_pre].add(jnp.where(rec, k_row_f, 0.0))
            continue
        if gm.is_inverse:
            continue  # inverse groups record on OWNER placements only
        lo, hi = gm.seg
        allow_seg = m_allow_rows[:, lo:hi]
        out_k = m_out_rows[:, gm.key_k]
        rec = selp[g]
        singleton = (~out_k) & (allow_seg.sum(axis=-1) == 1)
        delta = allow_seg & singleton[:, None]  # [N, seg]
        inc = (delta.astype(jnp.float32) * k_row_f[:, None]).sum(axis=0)
        tcounts = tcounts.at[g, lo:hi].add(jnp.where(rec, inc, 0.0))
        newdoms = (delta & touched[:, None]).any(axis=0) & rec
        tdoms = tdoms.at[g, lo:hi].set(tdoms[g, lo:hi] | newdoms)
    return tcounts, thost, tdoms


def topo_record(
    meta: TopoMeta,
    tcounts,
    thost,
    tdoms,
    own,
    selp,
    nf_ok,
    m_allow,
    m_out,
    row_mask,
    k_row,
):
    """Commit a (possibly bulk) placement into counts (topology.go:120-143).

    nf_ok[G]: node-filter match of the group vs the merged slot requirements.
    m_allow/m_out: the committed merged requirement masks (identical for every
    committed slot — bulk commits write one merged row to a range of slots).
    row_mask[N]: slots written; k_row[N]: replicas placed per slot.
    Returns (new_counts, new_hcounts, new_domain_mask)."""
    import jax.numpy as jnp

    k_row_f = jnp.where(row_mask, k_row, 0).astype(jnp.float32)
    placed_total = k_row_f.sum()
    # a zero-placement call must be a strict NO-OP (commit sites run
    # unconditionally with predicated no-op values instead of lax.cond —
    # branch-carried state forced XLA to copy the big planes every commit);
    # domain registration therefore gates on an actual placement
    active = placed_total > 0
    for g, gm in enumerate(meta.groups):
        if gm.is_hostname:
            # each slot IS its (singleton) hostname domain
            rec = own[g] if gm.is_inverse else (selp[g] & nf_ok[g])
            thost = thost.at[g].add(jnp.where(rec, k_row_f, 0.0))
            continue
        lo, hi = gm.seg
        allow_seg = m_allow[lo:hi]
        out_k = m_out[gm.key_k]
        # Requirement.Values(): allowed values for In-sets, EXCLUDED values
        # for complement sets (requirement.go:178-180) — mirrored exactly.
        vals = jnp.where(out_k, ~allow_seg, allow_seg)
        if gm.is_inverse:
            rec = own[g]
            delta = vals
        else:
            rec = selp[g] & nf_ok[g]
            if gm.gtype == TOPO_ANTI:
                delta = vals
            else:
                singleton = (~out_k) & (allow_seg.sum() == 1)
                delta = allow_seg & singleton
        inc = (rec & delta).astype(jnp.float32) * placed_total
        tcounts = tcounts.at[g, lo:hi].add(inc)
        tdoms = tdoms.at[g, lo:hi].set(tdoms[g, lo:hi] | (rec & delta & active))
    return tcounts, thost, tdoms


def topo_node_filter_ok(meta: TopoMeta, terms, segments, well_known, m_allow, m_out, m_defined):
    """[G] bool: TopologyNodeFilter.MatchesRequirements(merged slot reqs)
    (topologynodefilter.go:46-56): empty filter matches; else any term where
    Compatible(merged, term) passes."""
    import jax.numpy as jnp

    from karpenter_core_tpu.ops import compat

    if terms is None or terms["allow"].shape[0] == 0:
        return jnp.ones(len(meta.groups), dtype=bool)

    m_escape = compat.escape_flags(m_allow[None], m_out[None], m_defined[None], segments)[0]
    node = {
        "allow": m_allow[None, :],
        "out": m_out[None, :],
        "defined": m_defined[None, :],
        "escape": m_escape[None, :],
    }
    # direction: Compatible(node=merged slot reqs, incoming=term)
    ok_rows = compat.pairwise_compatible(node, terms, segments, well_known)[0]  # [GT]
    out = []
    for gm in meta.groups:
        if not gm.filter_term_rows:
            out.append(jnp.bool_(True))
        else:
            out.append(jnp.any(jnp.stack([ok_rows[r] for r in gm.filter_term_rows])))
    return jnp.stack(out)
