"""Greedy packing kernel: lax.scan over FFD-ordered pods.

Replaces the serial Solve loop (reference scheduler.go:96-133,177-222) with a
device-resident scan over a fixed budget of node slots:

  slot state: accumulated requests, merged requirement masks, remaining
  instance-type mask, per-resource optimistic max-allocatable, pod count;
  global state: per-topology-group domain counts (ops/topology.py).

Per pod step:
  1. SCREEN all slots cheaply: taints ∧ requirement-compat ∧ optimistic fit
     (used + pod <= per-slot max over remaining types) ∧ topology viability.
  2. Rank candidates by the reference's order: existing nodes (index order)
     first, then open machines ascending pod count (scheduler.go:179-193).
  3. VERIFY the best candidate exactly: merge slot ∪ pod requirements,
     narrow by the topology domain choice (skew-rule argmin domain etc.),
     recompute the surviving instance types (compatible ∧ fits ∧ offering,
     machine.go:137-159). On failure, mask the candidate and retry (bounded
     while_loop).
  4. Otherwise OPEN a new slot from the first template whose fresh machine
     (fresh hostname domain) can host the pod (weight order,
     scheduler.go:195-221), honoring provisioner limits via pessimistic
     max-capacity subtraction (scheduler.go:276-293).
  5. COMMIT: update slot state and record the placement into topology domain
     counts (topology.go:120-143).

Slots [0, E) are pre-seeded with existing nodes (fixed capacity, no type
narrowing); machine slots open from E upward. Machine slot n's hostname
domain is the pre-registered dictionary value slot-hostname-n.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from karpenter_core_tpu.ops import compat
from karpenter_core_tpu.ops import topology as topo

BIG = jnp.float32(1e30)


class PackState(NamedTuple):
    used: jnp.ndarray  # [N, R]
    open: jnp.ndarray  # [N] bool
    is_existing: jnp.ndarray  # [N] bool
    tmpl: jnp.ndarray  # [N] int32 template id (machine slots)
    tol_idx: jnp.ndarray  # [N] int32 row into pod_tol_all
    pods: jnp.ndarray  # [N] int32
    allow: jnp.ndarray  # [N, V] bool (merged requirement masks)
    out: jnp.ndarray  # [N, K] bool
    defined: jnp.ndarray  # [N, K] bool
    tmask: jnp.ndarray  # [N, T] bool (remaining instance types; machine slots)
    cap: jnp.ndarray  # [N, R] optimistic capacity: existing=available,
    #                   machine=max over remaining types' allocatable
    nopen: jnp.ndarray  # scalar int32 — next free slot
    remaining: jnp.ndarray  # [J, R] provisioner remaining limit (+inf if none)
    tcounts: jnp.ndarray  # [G, V] topology domain counts (value-key groups)
    thost: jnp.ndarray  # [G, N] hostname-group counts per slot
    tdoms: jnp.ndarray  # [G, V] registered domains per group
    ports: jnp.ndarray  # [N, Q] reserved host-port entries (Q=0 when unused)
    vols: jnp.ndarray  # [E_pad, W] mounted volume claims (existing slots only)


def _segment_max_alloc(tmask: jnp.ndarray, type_alloc: jnp.ndarray) -> jnp.ndarray:
    """[..., T] bool, [T, R] -> [..., R] max allocatable over allowed types."""
    masked = jnp.where(tmask[..., None], type_alloc, -BIG)
    return masked.max(axis=-2)


def make_screen_ops(segments, backend, screen_v):
    """Lowerings for the batched class×slot requirement screen — the
    prescreen path's analog of slot_compat_screen, with the item axis as
    the batch instead of the slot axis.

    Semantics are Requirements.Compatible(slot row = node side, item row =
    pod side), bit-identical to the per-step screen for the same backend.
    ALL backends slice the hostname tail off at screen_v here — exact to
    skip, because when elision engages no item defines (or custom-denies)
    the elided keys, so every such key term resolves through ~shared
    regardless of the slot planes; the tiered sliced screen runs full
    width but computes the same verdicts. All forms evaluate through bf16
    matmuls with f32 accumulation — exact for 0/1 indicator masks."""
    seg_mats = {}

    def _w(V):
        if screen_v is None:
            return V
        return min(screen_v, V)

    def _sm(V):
        w = _w(V)
        if (V, w) not in seg_mats:
            seg_mats[(V, w)] = jnp.asarray(compat.seg_matrix(segments, V)[:w])
        return seg_mats[(V, w)]

    def items_vs_row(items, s_allow, s_out, s_defined):
        """[I] verdict of every item against ONE slot row — the refresh
        unit for a candidate commit (one narrowed slot) and for the shared
        merged row a bulk open writes across its fresh slots."""
        V = s_allow.shape[0]
        w = _w(V)
        sm = _sm(V)
        s_esc = compat.escape_flags_m(
            s_allow[None, :w], s_out[None], s_defined[None], sm
        )[0]
        inter = compat.segment_any_m(
            items["allow"][:, :w] & s_allow[None, :w], sm
        )
        shared = items["defined"] & s_defined[None, :]
        both_out = items["out"] & s_out[None, :]
        escapes = items["escape"] & s_esc[None, :]
        ok = ((~shared) | both_out | inter | escapes).all(axis=-1)
        return ok & ~jnp.any(
            items["custom_deny"] & ~s_defined[None, :], axis=-1
        )

    def rows_vs_items(items, s_allow, s_out, s_defined):
        """[B, I] pairwise verdict block: a batch of slot rows against
        every item — the bulk-region refresh, the pending drain, and the
        (matmul) precompute. One MXU contraction per key over the
        dictionary planes, with the slot rows as the LEFT operand so the
        result is produced NATIVELY in the verdict tensor's slot-major
        layout (a .T on an item-major form made XLA thread two layouts
        through the scan and insert a physical transpose copy of the whole
        tensor per step)."""
        V = s_allow.shape[1]
        w = _w(V)
        sm = _sm(V)
        s_esc = compat.escape_flags_m(s_allow[:, :w], s_out, s_defined, sm)
        B = s_allow.shape[0]
        I = items["allow"].shape[0]
        ok = jnp.ones((B, I), dtype=bool)
        for k, (lo, hi) in enumerate(segments):
            if lo >= w:
                continue  # elided hostname tail: resolves through ~shared
            hi_w = min(hi, w)
            shared = s_defined[:, k : k + 1] & items["defined"][None, :, k]
            both_out = s_out[:, k : k + 1] & items["out"][None, :, k]
            if hi_w > lo:
                inter = (
                    jnp.matmul(
                        s_allow[:, lo:hi_w].astype(jnp.bfloat16),
                        items["allow"][:, lo:hi_w].astype(jnp.bfloat16).T,
                        preferred_element_type=jnp.float32,
                    )
                    > 0.5
                )
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = s_esc[:, k : k + 1] & items["escape"][None, :, k]
            ok &= (~shared) | nonempty | escapes
        denied = (
            jnp.matmul(
                (~s_defined).astype(jnp.bfloat16),
                items["custom_deny"].astype(jnp.bfloat16).T,
                preferred_element_type=jnp.float32,
            )
            > 0.5
        )
        return ok & ~denied

    def initial_screen(items, e_allow, e_out, e_defined, n_slots):
        """[N, I] slot-major verdict tensor for the scan-entry slot state:
        the exact pairwise block over the existing prefix plus the virgin
        verdict row broadcast over the (still closed, hence
        unread-until-opened) machine region. On the Pallas backend the
        block reuses the fused screen kernel in its batched (item-major)
        form, transposed once."""
        I = items["allow"].shape[0]
        V = e_allow.shape[1]
        K = e_out.shape[1]
        E = e_allow.shape[0]
        if E and backend == "pallas":
            from karpenter_core_tpu.ops import pallas_kernels

            w = _w(V)
            block = pallas_kernels.batched_slot_screen_pallas(
                e_allow[:, :w], e_out, e_defined,
                dict(items, allow=items["allow"][:, :w]),
                _sm(V),
            ).T
        elif E:
            block = rows_vs_items(items, e_allow, e_out, e_defined)
        else:
            block = jnp.zeros((0, I), dtype=bool)
        virgin = items_vs_row(
            items,
            jnp.ones(V, dtype=bool),
            jnp.ones(K, dtype=bool),
            jnp.zeros(K, dtype=bool),
        )
        tail = jnp.broadcast_to(virgin[None, :], (n_slots - E, I))
        return jnp.concatenate([block, tail], axis=0)

    class _Ops:
        pass

    ops = _Ops()
    ops.items_vs_row = items_vs_row
    ops.rows_vs_items = rows_vs_items
    ops.initial_screen = initial_screen
    return ops


def make_prescreen_kernel(segments, n_slots, backend=None, screen_v=None,
                          spec_layout=None):
    """Build the standalone jittable prescreen: (pod item planes, existing
    planes) -> [N, C] slot-major verdict tensor over the deduped class
    columns (pod_arrays["scls_first"], identity when absent). TPUSolver
    dispatches this as its own (geometry-cached) program so the precompute
    is host-visible as the solver.phase.prescreen span; pack() computes the
    identical tensor internally when no screen0 is handed in
    (rung/service paths).

    spec_layout (parallel/specs.SpecLayout) turns this into a GSPMD mesh
    program: the existing-slot rows constrain over 'dp' and the class
    columns over 'tp', so the bf16 screen contractions compute as
    communication-free (dp x tp) tiles of the [N, C] tensor; the final
    gather is the one XLA-inserted all_gather that reassembles the rows
    for the (replicated) pack scan. Sharding only tiles output axes —
    never a contraction axis — so the tensor is byte-identical to the
    single-device program's."""
    backend = backend or compat.resolve_backend()
    ops = make_screen_ops(list(segments), backend, screen_v)

    def prescreen(pod_arrays, exist):
        sf = pod_arrays.get("scls_first")
        items = {
            k: (pod_arrays[k] if sf is None else pod_arrays[k][sf])
            for k in ("allow", "out", "defined", "escape", "custom_deny")
        }
        e_allow, e_out, e_def = exist["allow"], exist["out"], exist["defined"]
        if spec_layout is not None:
            ly = spec_layout
            cols = ly.type_plane()  # class-column rows ride the tp family
            items = {k: ly.constrain(v, cols) for k, v in items.items()}
            rows = ly.slot_plane()
            e_allow = ly.constrain(e_allow, rows)
            e_out = ly.constrain(e_out, rows)
            e_def = ly.constrain(e_def, rows)
        scr = ops.initial_screen(items, e_allow, e_out, e_def, n_slots)
        if spec_layout is not None:
            scr = spec_layout.constrain(scr, spec_layout.verdict())
            # gather + process-unique persistent-cache key on CPU
            # (specs.SpecLayout.cache_salt — semantic no-op)
            scr = spec_layout.cache_salt(spec_layout.gather(scr))
        return scr

    return prescreen


def make_screen_refresh_kernel(segments, n_slots, rb: int, cb: int,
                               backend=None, screen_v=None,
                               spec_layout=None):
    """Delta refresh of a RESIDENT [N, C] verdict tensor — the incremental
    re-solve path's device program (solver/incremental.py).

    verdict[n, c] is a pure function of (slot row n's planes, class column
    c's planes), so a steady-state solve whose geometry matches the
    previous one only needs to recompute the rows whose existing-slot
    planes changed (narrowed / freed / replaced slots) and the columns
    whose class planes changed (new or relaxed items); everything else
    carries over bit-for-bit. rb/cb are the padded row/column delta
    budgets (the compiled signature); indices beyond the live count are
    dropped via OOB-scatter semantics. Device cost is O(rb*C + E*cb)
    contractions instead of the full O(N*C) precompute — it scales with
    the CHURN, not the world.

    Row updates re-screen a changed slot row against ALL columns; column
    updates recompute the full column (pairwise block over the existing
    prefix + the virgin-row value broadcast over the machine region, the
    exact construction initial_screen uses). Overlapping (row, col) cells
    are written twice with the same value, so update order is immaterial.
    Semantics are bool-exact vs make_prescreen_kernel: both evaluate the
    same 0/1 indicator algebra through the same screen ops.

    spec_layout (the GSPMD mesh path): the refresh pins EVERYTHING
    replicated — inputs, scatters, output. The compute is delta-sized so
    sharding it buys nothing, and the pin is the same correctness fence
    the pack scan needs: with mesh-committed inputs (the resident tensor
    is a mesh-program output) the auto-partitioned scatter miscomputed on
    the CPU backend, which surfaced as stale verdict columns on the
    second-and-later solves of a steady-state mesh churn sequence."""
    backend = backend or compat.resolve_backend()
    ops = make_screen_ops(list(segments), backend, screen_v)

    def refresh(prev_screen, pod_arrays, exist, row_idx, row_n, col_idx,
                col_n):
        if spec_layout is not None:
            g = spec_layout.gather
            prev_screen = g(prev_screen)
            pod_arrays = {k: g(v) for k, v in pod_arrays.items()}
            exist = {k: g(v) for k, v in exist.items()}
            row_idx, col_idx = g(row_idx), g(col_idx)
        sf = pod_arrays.get("scls_first")
        items = {
            k: (pod_arrays[k] if sf is None else pod_arrays[k][sf])
            for k in ("allow", "out", "defined", "escape", "custom_deny")
        }
        C = items["allow"].shape[0]
        V = items["allow"].shape[1]
        K = items["out"].shape[1]
        E = exist["allow"].shape[0]
        N = n_slots
        scr = prev_screen
        if rb and E:
            # changed existing rows x ALL columns
            gi = jnp.clip(row_idx, 0, max(E - 1, 0))
            row_block = ops.rows_vs_items(
                items, exist["allow"][gi], exist["out"][gi],
                exist["defined"][gi],
            )  # [rb, C]
            on_r = jnp.arange(rb) < row_n
            target = jnp.where(on_r, row_idx, N)  # OOB rows drop
            scr = scr.at[target].set(row_block, mode="drop")
        if cb:
            # changed columns x ALL rows: existing block + virgin tail
            gc = jnp.clip(col_idx, 0, max(C - 1, 0))
            col_items = {k: v[gc] for k, v in items.items()}
            blk = ops.rows_vs_items(
                col_items, exist["allow"], exist["out"], exist["defined"]
            )  # [E, cb]
            virgin = ops.items_vs_row(
                col_items,
                jnp.ones(V, dtype=bool),
                jnp.ones(K, dtype=bool),
                jnp.zeros(K, dtype=bool),
            )  # [cb]
            full_col = jnp.concatenate(
                [blk, jnp.broadcast_to(virgin[None, :], (N - E, cb))], axis=0
            )  # [N, cb]
            on_c = jnp.arange(cb) < col_n
            tcol = jnp.where(on_c, col_idx, C)  # OOB columns drop
            scr = scr.at[:, tcol].set(full_col, mode="drop")
        if spec_layout is not None:
            # process-unique persistent-cache key on CPU (semantic no-op;
            # specs.SpecLayout.cache_salt)
            scr = spec_layout.cache_salt(scr)
        return scr

    return refresh


def make_replan_verdict_kernel(n_exist: int):
    """Per-subset verdict reduction for the batched consolidation replan
    (solver/replan.py): the consolidation search only ever reads FOUR
    scalars per candidate subset — how many evicted/pending pods re-packed,
    how many were supposed to, how many NEW machine slots opened, and
    whether an uninitialized existing node absorbed pods (inconclusive,
    helpers.go:41-105's in-flight-node rule). Reducing on the device keeps
    the per-dispatch fetch at [K, 4] int32 instead of the [K, N] slot
    plane — on the 10k-node geometry that is bytes instead of megabytes
    over a link that charges per round trip."""

    def verdict(pods_per_slot, count_row, uninit):
        scheduled = pods_per_slot.sum()
        expected = count_row.sum()
        n_new = (pods_per_slot[n_exist:] > 0).sum()
        incon = (pods_per_slot[:n_exist] * uninit[:n_exist]).sum() > 0
        return jnp.stack(
            [scheduled, expected, n_new, incon.astype(jnp.int32)]
        ).astype(jnp.int32)

    return verdict


def make_batched_replan_kernel(rung_run, n_exist: int, external_screen: bool):
    """The candidate-axis batched replan program: K candidate node-subsets
    evaluated as ONE device call (ISSUE 10 tentpole).

    rung_run is a rung-mode solve program (tpu_solver.make_device_run with
    rung_mode=True): per subset, `exist_open` reopens the victims' slots
    out of the cluster (False = the candidate's existing slot closes) and
    `count_row` activates the victims' evicted pods on the item axis; the
    full pack scan then re-packs them against the residual cluster. The
    candidate axis enters ONLY through those two [K, ...] planes — every
    slot/type/template plane is shared across subsets, so vmap broadcasts
    one copy and the feasibility/prescreen precompute traces once.

    external_screen threads a caller-dispatched [N, C] prescreen verdict
    tensor (screen0) through every subset UNBATCHED: the verdict is
    candidate-invariant (closing a slot changes its openness, never its
    requirement planes), which is what lets the solver's RESIDENT tensor —
    maintained across solves by solver/incremental.py's refresh kernel —
    serve all K simulated re-packs of a consolidation pass.

    Returns replan(count_rows [K, I], exist_open [K, E], uninit [E],
    screen0, *run_args) -> (pods_per_slot [K, N] int32, verdicts [K, 4]
    int32 — see make_replan_verdict_kernel)."""
    verdict_of = make_replan_verdict_kernel(n_exist)

    def replan(count_rows, exist_open, uninit, screen0, *run_args):
        def one(count_row, open_row):
            if external_screen:
                _log, _ptr, state = rung_run(
                    count_row, open_row, screen0, *run_args
                )
            else:
                _log, _ptr, state = rung_run(count_row, open_row, *run_args)
            return state.pods, verdict_of(state.pods, count_row, uninit)

        return jax.vmap(one)(count_rows, exist_open)

    return replan


def make_segment_partition_kernel(segments, n_exist: int,
                                  screen_v: Optional[int] = None,
                                  backend: Optional[str] = None,
                                  spec_layout=None):
    """Device-side segment partitioner (ISSUE 14 tentpole): label every
    verdict-tensor class column with its CONFLICT COMPONENT, so the solver
    can pack independent components in parallel lanes and still be
    byte-identical to the sequential scan.

    Two classes conflict iff
      * their feasible EXISTING-slot sets intersect (read straight off the
        resident [N, C] verdict tensor's existing prefix — the PR 5/6
        precompute is exactly the conflict structure), or
      * their template requirement-verdicts intersect (both could land on —
        or open — a machine of the same template: a machine row's planes
        are always a NARROWING of its template's, and the requirement
        algebra is monotone under narrowing except for the deny channel
        below, so the template verdict is a superset of reachability), or
      * one DEFINES a custom key the other custom-DENIES (the one
        non-monotone channel: a commit that defines a custom key on a slot
        LIFTS the Compatible() deny for classes that require that key —
        requirements.go:123-133 — so a verdict can flip False -> True on
        exactly those (definer, denier) pairs).

    All three tests are conservative SUPERSETS of runtime interaction:
    capacity, tolerations, scoring and skew only ever REMOVE candidates,
    and plane merges only ever narrow the remaining terms, so a missing
    edge proves the sequential scan could never have routed one class's
    pods through the other's slots or machines. That proof is what makes
    the per-segment results literally equal the sequential results
    restricted to the segment (modulo machine-slot renumbering, which the
    host merge replays in global item order). The predicate deliberately
    does NOT need a mutates-a-plane catch-all: plane-mutating items stay
    segmentable because the lanes run the full in-scan refresh machinery,
    and their mutations land only on slots/machines already inside their
    own component.

    Returns (labels [C] int32 — component id per class column, neutral
    [C] bool — no defined keys inside the screen width, slot_label [E]
    int32 — owning component per existing slot, -1 when no class is
    feasible there)."""
    backend = backend or compat.resolve_backend()
    ops = make_screen_ops(list(segments), backend, screen_v)
    seg_list = list(segments)

    def partition(screen0, pod_arrays, tmpl, well_known):
        if spec_layout is not None:
            g = spec_layout.gather
            screen0 = g(screen0)
            pod_arrays = {k: g(jnp.asarray(v)) for k, v in pod_arrays.items()}
            tmpl = {k: g(jnp.asarray(v)) for k, v in tmpl.items()}
            well_known = g(well_known)
        sf = jnp.asarray(pod_arrays["scls_first"])
        items = {
            k: jnp.asarray(pod_arrays[k])[sf]
            for k in ("allow", "out", "defined", "escape", "custom_deny")
        }
        C = items["allow"].shape[0]
        V = items["allow"].shape[1]
        WSCR = V if screen_v is None else min(screen_v, V)
        key_scr = jnp.asarray([lo < WSCR for (lo, _hi) in seg_list])
        neutral = ~jnp.any(items["defined"] & key_scr[None, :], axis=-1)
        tmpl_rows = ops.rows_vs_items(
            items, tmpl["allow"], tmpl["out"], tmpl["defined"]
        )  # [J, C]
        t = tmpl_rows.astype(jnp.bfloat16)
        conf = (
            jnp.matmul(t.T, t, preferred_element_type=jnp.float32) > 0.5
        )  # [C, C]
        if n_exist:
            a = screen0[:n_exist].astype(jnp.bfloat16)
            conf |= (
                jnp.matmul(a.T, a, preferred_element_type=jnp.float32) > 0.5
            )
        # the deny-lift channel, per key: class c defines a custom key k
        # (any defined merge makes its slots define k — In, NotIn and DNE
        # alike), class c' custom-denies k, AND their value sets on k can
        # actually intersect (the lifted slot's k-plane is always a subset
        # of c's allow, so an empty c∩c' intersection proves the k-term
        # still fails after the lift — disjoint selector pools stay
        # disjoint). Zero-width / complement-only keys fall back to the
        # both_out term, same shape as the screen algebra itself.
        lift = jnp.zeros((C, C), dtype=bool)
        for k, (lo, hi) in enumerate(seg_list):
            pair = (
                items["defined"][:, k : k + 1]
                & ~well_known[k]
                & items["custom_deny"][None, :, k]
            )  # [C, C]: definer rows x denier columns
            both_out = items["out"][:, k : k + 1] & items["out"][None, :, k]
            if hi > lo:
                inter = (
                    jnp.matmul(
                        items["allow"][:, lo:hi].astype(jnp.bfloat16),
                        items["allow"][:, lo:hi].astype(jnp.bfloat16).T,
                        preferred_element_type=jnp.float32,
                    )
                    > 0.5
                )
                nonempty = both_out | inter
            else:
                nonempty = both_out
            lift |= pair & nonempty
        conf = conf | lift | lift.T
        conf |= jnp.eye(C, dtype=bool)

        # connected components by min-label propagation: converges in at
        # most the component diameter (<= C) rounds; the while_loop stops
        # at the fixpoint, which real workloads reach in a handful
        def w_cond(c):
            return c[1]

        def w_body(c):
            labels, _ = c
            new = jnp.min(
                jnp.where(conf, labels[None, :], jnp.int32(C)), axis=-1
            ).astype(jnp.int32)
            new = jnp.minimum(new, labels)
            return new, jnp.any(new != labels)

        labels, _ = jax.lax.while_loop(
            w_cond, w_body, (jnp.arange(C, dtype=jnp.int32), jnp.bool_(True))
        )
        if n_exist:
            se = screen0[:n_exist]
            slot_label = jnp.min(
                jnp.where(se, labels[None, :], jnp.int32(C)), axis=-1
            )
            slot_label = jnp.where(
                slot_label == C, jnp.int32(-1), slot_label
            ).astype(jnp.int32)
        else:
            slot_label = jnp.zeros((0,), jnp.int32)
        if spec_layout is not None:
            # process-unique persistent-cache key on CPU (semantic no-op;
            # specs.SpecLayout.cache_salt — multi-device executables only)
            labels = spec_layout.cache_salt(labels)
        return labels, neutral, slot_label

    return partition


def make_pack_kernel(
    segments,
    zone_seg,
    ct_seg,
    topo_meta: Optional[topo.TopoMeta] = None,
    backend: Optional[str] = None,
    screen_v: Optional[int] = None,
    screen_mode: Optional[str] = None,
):
    """Build the jittable packing fn for a fixed label geometry (+ topology
    group structure when the batch has topology constraints).

    backend ∈ {'sliced', 'mxu', 'pallas'} picks the lowering for the device
    the program will run on (compat.resolve_backend); None resolves from the
    default backend. Explicit so a CPU trace targeting TPU (or a test forcing
    the MXU form on CPU) gets the right branch.

    screen_v: the MXU screens' value-axis width. When the encoder proves no
    pod or instance type constrains hostname, the (last, ~half-of-V on a
    real cluster) hostname segment drops out of the screen matmuls — every
    hostname key term resolves through ~shared regardless of content, so
    the sliced screens are exact. None or >= V means full width; the
    'sliced' CPU lowering always runs full width (same semantics).

    screen_mode ∈ {'tiered', 'prescreen'} (compat.resolve_screen_mode when
    None). 'prescreen' hoists the per-step requirement screen out of the
    scan: a [I items × N slots] verdict tensor is computed ONCE before the
    scan (make_screen_ops.initial_screen — or handed in via pack's screen0
    argument by a caller that dispatched it as its own program) and each
    step GATHERS its row; commits refresh only the slot row(s) they wrote
    — O(1 slot-row) re-screens instead of the O(N×V×K) per-step full
    screen, gated off entirely for items that cannot change the
    requirement planes (no defined keys, no topology involvement).
    'tiered' keeps the original per-step screen as the fallback."""
    backend = backend or compat.resolve_backend()
    assert backend in ("sliced", "mxu", "pallas"), backend
    mxu = backend in ("mxu", "pallas")
    screen_mode = screen_mode or compat.resolve_screen_mode()
    assert screen_mode in ("tiered", "prescreen"), screen_mode
    prescreen = screen_mode == "prescreen"
    screen_ops = (
        make_screen_ops(list(segments), backend, screen_v) if prescreen else None
    )

    zlo, zhi = zone_seg
    clo, chi = ct_seg
    has_topo = topo_meta is not None and len(topo_meta.groups) > 0
    # machine-region bulk fill: when the batch carries hostname anti-affinity
    # groups (slot-local — the domain IS the node), their classes stay bulk
    # (solver/encode._build_items) and the bulk-fill region widens from the
    # existing prefix to the FULL slot axis, with exact per-slot type
    # narrowing for machine rows. Without this, each of a service's
    # one-replica-per-node pods pays one while-iteration (a ~310-replica
    # hostname-anti service = ~310 candidate commits); with it the whole
    # class commits in one iteration. Geometries without hostname anti
    # compile the exact same program as before.
    # trigger ONLY on hostname anti: widening to every topology geometry was
    # measured 3.2x SLOWER at the 50k headline (918ms -> 2970ms warm p50) —
    # ~1000 generic classes each paid the [MBW, T] exact machine narrowing
    # per bulk iteration, swamping the saved per-slot commits. Anti-bearing
    # batches have few classes and k=1-per-slot items, where the trade wins.
    mach_bulk = has_topo and any(
        gm.gtype == topo.TOPO_ANTI and gm.is_hostname
        for gm in topo_meta.groups
    )
    # value-key spread groups: bulk items owning one are packed by a
    # per-iteration water-fill domain allocation (greedy argmin-count per pod
    # equalizes domain counts, so the bulk final state matches per-pod greedy)
    vk_spread_gs = (
        [
            (g, gm)
            for g, gm in enumerate(topo_meta.groups)
            if gm.gtype == topo.TOPO_SPREAD and not gm.is_hostname
        ]
        if has_topo
        else []
    )
    seg_mat = None  # [V, K] built lazily at trace time (V known from arrays)

    def _sv(V):
        """Screen width for a full value axis of V."""
        return V if screen_v is None else min(screen_v, V)

    def _seg_mat(V):
        nonlocal seg_mat
        if seg_mat is None:
            seg_mat = compat.seg_matrix(segments, V)
        return seg_mat[: _sv(V)]

    def slot_compat_screen(allow, out, defined, prow):
        """[n] bool: pod-vs-slot requirement compatibility + custom rule
        (the node side is the slot's merged requirements). Takes the slot
        planes directly — callers pass a PREFIX of the slot axis (the
        nopen-tiered screen) or the full planes.

        On MXU backends the per-key any-reductions fuse into 3 matmuls
        (op-count is what bounds the scan step) — or into ONE Pallas pass
        over the allow tile when enabled; on CPU the sliced loop form is
        faster, so pick per backend at trace time."""
        if mxu:
            V_full = allow.shape[1]
            svv = _sv(V_full)
            sm = _seg_mat(V_full)
            allow_s = allow[:, :svv]
            prow_s = dict(prow, allow=prow["allow"][:svv])
            if backend == "pallas":
                from karpenter_core_tpu.ops import pallas_kernels

                return pallas_kernels.slot_screen_pallas(
                    allow_s, out, defined, prow_s, sm
                )
            return compat.rows_compat_m(
                {"allow": allow_s, "out": out, "defined": defined},
                prow_s,
                sm,
                custom_deny=prow["custom_deny"],
            )
        ok = jnp.ones(allow.shape[0], dtype=bool)
        slot_escape = compat.escape_flags(allow, out, defined, segments)
        for k, (lo, hi) in enumerate(segments):
            shared = defined[:, k] & prow["defined"][k]
            both_out = out[:, k] & prow["out"][k]
            if hi > lo:
                inter = (allow[:, lo:hi] & prow["allow"][lo:hi]).any(axis=-1)
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = slot_escape[:, k] & prow["escape"][k]
            ok &= (~shared) | nonempty | escapes
        # custom keys the pod defines (op not NotIn/DNE) must be defined on slot
        deny = prow["custom_deny"]  # [K]
        ok &= ~jnp.any(deny[None, :] & ~defined, axis=-1)
        return ok

    def merged_types_compat(m_allow, m_out, m_defined, base_tmask, type_reqs,
                            type_offering_ok):
        """[T]: requirement/offering-surviving types for a merged row
        (compatible ∧ hasOffering — machine.go:137-159; resource fit is
        handled separately through per-type replica capacities)."""
        if mxu:
            V_full = m_allow.shape[0]
            svv = _sv(V_full)
            sm = _seg_mat(V_full)
            m_allow_s = m_allow[:svv]
            m_escape = compat.escape_flags_m(
                m_allow_s[None], m_out[None], m_defined[None], sm
            )[0]
            ok_t = compat.row_vs_rows_compat_m(
                m_allow_s, m_out, m_defined, m_escape,
                dict(type_reqs, allow=type_reqs["allow"][:, :svv]), sm,
            )
        else:
            m_escape = compat.escape_flags(
                m_allow[None], m_out[None], m_defined[None], segments
            )[0]
            ok_t = jnp.ones(base_tmask.shape[0], dtype=bool)
            for k, (lo, hi) in enumerate(segments):
                shared = m_defined[k] & type_reqs["defined"][:, k]
                both_out = m_out[k] & type_reqs["out"][:, k]
                if hi > lo:
                    inter = (m_allow[lo:hi][None, :] & type_reqs["allow"][:, lo:hi]).any(
                        axis=-1
                    )
                    nonempty = both_out | inter
                else:
                    nonempty = both_out
                escapes = m_escape[k] & type_reqs["escape"][:, k]
                ok_t &= (~shared) | nonempty | escapes
        offer_t = (
            jnp.einsum(
                "tzc,z,c->t",
                type_offering_ok.astype(jnp.float32),
                m_allow[zlo:zhi].astype(jnp.float32),
                m_allow[clo:chi].astype(jnp.float32),
            )
            > 0.5
        )
        return base_tmask & ok_t & offer_t

    BIGK = jnp.int32(2**30)

    def replica_cap(alloc, used, req):
        """alloc [T, R] vs used [1, R] + k*req [R]: max k per row with
        exact-fit semantics (float floor corrected ±1 so k*req <= room holds
        under f32 algebra). Rows with any negative allocatable (invalid
        marker) or an already-overflowing req==0 resource get 0."""
        room = alloc - used  # [T, R]
        safe = req > 0  # [R]
        denom = jnp.where(safe, req, 1.0)
        kf = jnp.clip(jnp.floor(room / denom), 0.0, jnp.float32(BIGK))
        kf = jnp.where((kf + 1.0) * denom <= room, kf + 1.0, kf)
        kf = jnp.where(kf * denom > room, kf - 1.0, kf)
        k = jnp.clip(kf, 0.0, jnp.float32(BIGK)).astype(jnp.int32)
        k = jnp.where(safe, k, BIGK)
        kmin = k.min(axis=-1)
        valid = jnp.all((alloc >= 0.0) & ((room >= 0.0) | safe), axis=-1)
        return jnp.where(valid, kmin, 0)

    def replica_cap_rows(alloc, used_rows, req):
        """replica_cap vectorized over slot rows: alloc [T, R] vs per-slot
        used [BR, R] + k*req [R] -> [BR, T] max identical replicas, with the
        same exact-fit float corrections and validity rules. Looped over the
        (small, static) resource axis so the peak temp stays [BR, T]."""
        BRr = used_rows.shape[0]
        T = alloc.shape[0]
        bigf = jnp.float32(BIGK)
        kmin = jnp.full((BRr, T), bigf)
        valid = jnp.ones((BRr, T), dtype=bool)
        for r in range(alloc.shape[1]):
            alloc_r = alloc[None, :, r]  # [1, T]
            room = alloc_r - used_rows[:, r : r + 1]  # [BR, T]
            reqr = req[r]
            safe = reqr > 0
            denom = jnp.where(safe, reqr, 1.0)
            kf = jnp.clip(jnp.floor(room / denom), 0.0, bigf)
            kf = jnp.where((kf + 1.0) * denom <= room, kf + 1.0, kf)
            kf = jnp.where(kf * denom > room, kf - 1.0, kf)
            kr = jnp.where(safe, jnp.clip(kf, 0.0, bigf), bigf)
            kmin = jnp.minimum(kmin, kr)
            valid &= (alloc_r >= 0.0) & ((room >= 0.0) | safe)
        return jnp.where(valid, kmin, 0.0).astype(jnp.int32)

    def mach_rows_types_compat(m_allow_rows, m_out_rows, m_def_rows,
                               base_tmask_rows, type_reqs, type_offering_ok):
        """merged_types_compat vectorized over slot rows: [BR, T] bool of
        requirement/offering-surviving types per merged row (compatible ∧
        hasOffering, machine.go:137-159). The hostname tail beyond the
        screen width is exact to skip: instance types never define those
        keys, so every such key term resolves through ~shared."""
        V_full = m_allow_rows.shape[1]
        svv = _sv(V_full)
        a = m_allow_rows[:, :svv]
        t_allow = type_reqs["allow"][:, :svv]
        if mxu:
            esc = compat.escape_flags_m(
                a, m_out_rows, m_def_rows, _seg_mat(V_full)
            )
        else:
            esc = compat.escape_flags(
                m_allow_rows, m_out_rows, m_def_rows, segments
            )
        ok = jnp.ones(
            (m_allow_rows.shape[0], t_allow.shape[0]), dtype=bool
        )
        for k, (lo, hi) in enumerate(segments):
            if lo >= svv:
                continue
            hi_s = min(hi, svv)
            shared = m_def_rows[:, k : k + 1] & type_reqs["defined"][None, :, k]
            both_out = m_out_rows[:, k : k + 1] & type_reqs["out"][None, :, k]
            if hi_s > lo:
                inter = (
                    jnp.matmul(
                        a[:, lo:hi_s].astype(jnp.bfloat16),
                        t_allow[:, lo:hi_s].astype(jnp.bfloat16).T,
                        preferred_element_type=jnp.float32,
                    )
                    > 0.5
                )
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = esc[:, k : k + 1] & type_reqs["escape"][None, :, k]
            ok &= (~shared) | nonempty | escapes
        offer = (
            jnp.einsum(
                "tzc,nz,nc->nt",
                type_offering_ok.astype(jnp.float32),
                m_allow_rows[:, zlo:zhi].astype(jnp.float32),
                m_allow_rows[:, clo:chi].astype(jnp.float32),
            )
            > 0.5
        )
        return base_tmask_rows & ok & offer

    def _topo_skip(V, K):
        """The exact tuple topo_narrow_single returns when no group
        owns/selects the item: (viable, narrow[V], applied_keys[K], k_cap).
        Single definition — lax.cond branch shapes must stay in lockstep
        with the real call at every gated site."""
        return (
            jnp.bool_(True),
            jnp.ones(V, dtype=bool),
            jnp.zeros(K, dtype=bool),
            jnp.int32(BIGK),
        )

    def verify_slot(state: PackState, prow, n, type_reqs, type_alloc,
                    type_offering_ok, f_static_p, spread_force=None,
                    any_topo=None):
        """Exact acceptance check on slot n.
        Returns (ok, compat_tmask[T], kcap_t[T], kmax, narrow[V], applied[K]).
        kmax = max identical replicas slot n can take (capacity ∧ owned
        hostname-spread skew headroom).

        any_topo: item-invariant "owns or is selected by any topology group"
        flag (required when the kernel has topology groups); the whole
        per-group narrowing skips through one cond — the dominant
        topology-free items otherwise pay it on every verify iteration."""
        slot_allow = state.allow[n]
        K = state.out.shape[1]
        if has_topo:
            def _narrow(_):
                return topo.topo_narrow_single(
                    topo_meta, state.tcounts, state.thost, state.tdoms,
                    prow["topo_own"], prow["topo_sel"], prow["allow"],
                    slot_allow, n, K, spread_force=spread_force,
                )

            t_viable, narrow, applied_keys, k_topo = jax.lax.cond(
                any_topo, _narrow, lambda _: _topo_skip(slot_allow.shape[0], K),
                None,
            )
        else:
            t_viable, narrow, applied_keys, k_topo = _topo_skip(
                slot_allow.shape[0], K
            )

        m_allow = slot_allow & prow["allow"] & narrow
        # topology-narrowed keys become DEFINED concrete In-sets
        # (AddRequirements, topology.go:149-167)
        m_out = state.out[n] & prow["out"] & ~applied_keys
        m_defined = state.defined[n] | prow["defined"] | applied_keys

        compat_tmask = merged_types_compat(
            m_allow, m_out, m_defined,
            state.tmask[n] & f_static_p[state.tmpl[n]],
            type_reqs, type_offering_ok,
        )
        kcap_t = replica_cap(type_alloc, state.used[n][None, :], prow["requests"])
        is_existing = state.is_existing[n]
        kmax_exist = replica_cap(
            state.cap[n][None, :], state.used[n][None, :], prow["requests"]
        )[0]
        kmax_mach = jnp.max(jnp.where(compat_tmask, kcap_t, 0), initial=0)
        kmax = jnp.where(is_existing, kmax_exist, kmax_mach)
        kmax = jnp.minimum(kmax, k_topo)
        if "ports" in prow and prow["ports"].shape[0]:
            # a host-port pod conflicts with its own replicas on one node
            kmax = jnp.minimum(kmax, jnp.where(prow["ports"].any(), 1, BIGK))
        ok = t_viable & (kmax >= 1)
        return ok, compat_tmask, kcap_t, kmax, narrow, applied_keys

    def record_topo(state: PackState, prow, m_allow, m_out, m_defined,
                    well_known, terms, row_mask, k_row):
        if not has_topo:
            return state
        nf_ok = topo.topo_node_filter_ok(
            topo_meta, terms, segments, well_known, m_allow, m_out, m_defined
        )
        tcounts, thost, tdoms = topo.topo_record(
            topo_meta, state.tcounts, state.thost, state.tdoms,
            prow["topo_own"], prow["topo_sel"], nf_ok, m_allow, m_out,
            row_mask, k_row,
        )
        return state._replace(tcounts=tcounts, thost=thost, tdoms=tdoms)

    def pack(
        state: PackState,
        item_arrays: dict,
        f_static: jnp.ndarray,  # [J, I, T]
        openable: jnp.ndarray,  # [J, I]
        tmpl_reqs: dict,  # [J, ...]
        tmpl_daemon: jnp.ndarray,  # [J, R]
        tmpl_type_mask: jnp.ndarray,  # [J, T]
        type_reqs: dict,
        type_alloc: jnp.ndarray,
        type_capacity: jnp.ndarray,
        type_offering_ok: jnp.ndarray,
        well_known: jnp.ndarray = None,
        topo_terms: dict = None,
        log_len: int = None,
        n_exist: int = 0,
        vol_limits: jnp.ndarray = None,  # [E_pad, D]
        vol_driver: jnp.ndarray = None,  # [W, D] claim -> driver onehot
        log_commits: bool = True,
        screen0: jnp.ndarray = None,  # [N, C] precomputed verdict tensor
        item_ids: jnp.ndarray = None,  # [I] global item id per scan row
        screen_frozen: bool = False,  # all-neutral lanes: read-only verdicts
        bulk_len: int = None,  # override the bulk-take row budget
        class_planes: dict = None,  # [C, ...] verdict-column planes, when
        #                             the item axis is a gathered subset
        #                             (segmented lanes) and scls_first can
        #                             no longer index it
    ):
        N = state.used.shape[0]
        J = tmpl_daemon.shape[0]
        I = item_arrays["requests"].shape[0]
        V = state.allow.shape[1]
        K = state.out.shape[1]
        # host-port / volume axes: zero width compiles all checks away
        Q = state.ports.shape[1]
        W = state.vols.shape[1]
        EV = state.vols.shape[0]  # existing prefix carrying volume state
        # commit-log budget: every logged entry commits >= 1 replica, so
        # total pod count (+ slack) is the true bound — callers that know it
        # pass log_len (solve_geometry computes it). The fallback is a
        # heuristic only; commits are gated on log space either way, so an
        # undersized log fails the overflow pods cleanly instead of placing
        # them unlogged.
        L = log_len if log_len is not None else (4 * (I + N) + 64)
        # bulk existing-fill log: one [E] take-vector per bulk commit, the
        # main log carries an ns=-1 marker entry (k = bulk row index) so
        # decode replays commits in order. Budget: one bulk per item for the
        # topology-free case plus water-fill domain rounds (<= V-ish), hard-
        # capped so the [LB, E] matrix stays small at 50k-item scale — on
        # overflow the use_bulk gate falls back to the per-slot path, which
        # is slower but identical in result.
        # log_commits=False (the consolidation rung screen, which reads only
        # the final state) skips every log write AND the log-space gating,
        # so the bulk fast path runs with a 1-row take matrix.
        EB = n_exist
        # bulk-fill region: the existing prefix, widened to the full slot
        # axis when the geometry admits machine-region bulk items
        BR = N if mach_bulk else EB
        if BR == 0:
            LB = 1
        elif not log_commits:
            LB = 1
        elif bulk_len is not None:
            # segmented lanes size the take matrix to their own item count
            # (no vk-spread rounds there — topology disables segmentation),
            # keeping the vmapped [S, LB, BR] plane bounded
            LB = max(int(bulk_len), 1)
        elif mach_bulk:
            # take rows are per bulk COMMIT: <=~2 per plain bulk item
            # (fill + post-open leftovers) plus one per water-fill domain
            # round of vk-spread items (<= their total seg width); the old
            # V-based slack would blow the [LB, N] matrix up at wide-
            # dictionary geometries. Overflow falls back to the per-slot
            # path (identical result).
            spread_w = sum(
                gm.seg[1] - gm.seg[0]
                for gm in topo_meta.groups
                if gm.gtype == topo.TOPO_SPREAD and not gm.is_hostname
            )
            LB = min(3 * I + spread_w + 64, 2048)
        else:
            LB = min(2 * I + V + 64, 4096)

        log0 = {
            "item": jnp.full(L, -1, jnp.int32),
            "slot": jnp.zeros(L, jnp.int32),
            "ns": jnp.zeros(L, jnp.int32),
            "k": jnp.zeros(L, jnp.int32),
            "k_last": jnp.zeros(L, jnp.int32),
            "bulk_take": jnp.zeros((LB, BR), jnp.int32),
            "bulk_n": jnp.int32(0),
        }

        # prescreen: the class×slot verdict tensor rides the scan carry in
        # SLOT-MAJOR [N, C] layout — refreshes write whole slot rows, so
        # row-major contiguity must be on the slot axis (the item-major
        # form scattered one cache line per item per written slot,
        # ~8GB of write traffic at the 1000-class bench geometry). The
        # column axis C is the UNIQUE requirement class among items
        # (encode's item_scls/scls_items dedup): anti-affinity expansion
        # blows I up toward the pod count while C stays put, and every
        # expanded replica gathers its class's shared column. Each step
        # gathers its column instead of re-running the slot screen, and
        # commits refresh only the slot row(s) they wrote. The machine
        # region starts at the virgin-row value — entries there are never
        # read before an open (the screen ANDs with state.open) and the
        # open refresh overwrites them. class planes close over the scan as
        # constants: the refresh re-screens ALL classes against the written
        # slot row(s).
        item_arrays = dict(item_arrays)
        scls_first = item_arrays.pop("scls_first", None)
        if prescreen:
            if class_planes is not None:
                items_pl = dict(class_planes)
            else:
                if scls_first is None:  # identity: one column per item
                    scls_first = jnp.arange(I, dtype=jnp.int32)
                scls_first = jnp.asarray(scls_first)
                items_pl = {
                    k: jnp.asarray(item_arrays[k])[scls_first]
                    for k in ("allow", "out", "defined", "escape",
                              "custom_deny")
                }
            C = items_pl["allow"].shape[0]
            if screen_frozen:
                # segmented lane (ISSUE 14): every lane item is proven
                # plane-neutral by the partitioner, so no commit can change
                # any verdict. The tensor stays a scan CONSTANT (one shared
                # copy across all vmapped lanes) instead of riding the
                # carry, and the refresh-descriptor machinery compiles
                # away; opened MACHINE rows — whose tensor entries are
                # virgin and, in the sequential path, overwritten by the
                # open's refresh — read the precomputed tmpl_rows gather in
                # `step` instead (a neutral open writes exactly the
                # template's row).
                assert screen0 is not None, "frozen screen requires screen0"
                screen_init = screen0
            else:
                screen_init = (
                    screen0
                    if screen0 is not None
                    else screen_ops.initial_screen(
                        items_pl,
                        state.allow[:n_exist],
                        state.out[:n_exist],
                        state.defined[:n_exist],
                        N,
                    )
                )  # [N, C], slot-major
        else:
            items_pl = None
            C = 0
            screen_init = jnp.zeros((0, 0), dtype=bool)  # dead placeholder

        # per-template verdict columns, computed ONCE per solve: a plane-
        # neutral item (no defined keys, no topology) merges as the identity,
        # so the row an open writes for it IS the template's planes — the
        # dominant generic items gather this constant instead of paying an
        # items_vs_row contraction on every open
        if prescreen:
            if "scls" not in item_arrays:  # identity column per item
                item_arrays["scls"] = jnp.arange(I, dtype=jnp.int32)
        if prescreen:
            tmpl_rows = screen_ops.rows_vs_items(
                items_pl, tmpl_reqs["allow"], tmpl_reqs["out"],
                tmpl_reqs["defined"],
            )  # [J, C] — frozen mode reads these for opened machine rows
        else:
            tmpl_rows = None
        # refresh DESCRIPTOR. The verdict tensor must never be written
        # inside ANY lax.cond whose other branch leaves it unchanged — the
        # branch-buffer unification copies the whole [N, I] tensor per cond
        # evaluation. That rules out writes in the while-loop branches
        # (measured 444ms -> 2139ms at the 1000-class bench geometry) AND
        # anywhere inside the per-item valid/skip cond around _step_body
        # (~0.7ms/step in copies). So the step body only ACCUMULATES
        # refresh ops — (base row, run length, one [C] verdict row) per
        # commit, in iteration order so later writes of the same slot win —
        # and `step` applies them OUTSIDE the cond through a while loop of
        # blended dynamic-update-slice windows, the one update pattern XLA
        # reliably aliases in place. Whatever cannot fit the fixed budgets
        # (a bulk commit touching > UWB rows, an open wider than UWO, more
        # than SU ops in one step) lands in the descriptor's PENDING
        # interval instead, drained after the op replay by a cond-free
        # chunked re-screen — exact, because re-screening a slot row from
        # its current planes always yields the true verdict, and the tensor
        # is only read again at the next item's step entry.
        SU = 32  # refresh ops per step
        UWB = min(32, BR) if BR else 1  # bulk-refresh re-screen chunk
        UWO = min(64, N)  # max open run per op (also the apply window)
        DW = min(32, N)  # pending-drain chunk rows
        # screened value width + the keys whose segments fall inside it: a
        # commit that only narrows ELIDED keys (the encoder-proven hostname
        # tail, e.g. hostname-spread/anti narrowing) cannot change any
        # verdict — no item defines those keys — so its refresh is skipped
        # entirely via plane_mut
        WSCR = V if screen_v is None else min(screen_v, V)
        key_scr = jnp.asarray([lo < WSCR for (lo, _hi) in segments])

        def empty_desc():
            """No refresh ops, empty pending interval."""
            return (
                jnp.zeros((SU,), jnp.int32),  # base row per op
                jnp.zeros((SU,), jnp.int32),  # run length (0 = unused)
                jnp.zeros((SU, C), dtype=bool),  # verdict row per op
                jnp.int32(0),  # op cursor
                jnp.int32(N),  # pending lo
                jnp.int32(0),  # pending hi
            )

        def desc_pend(desc, on, lo, hi):
            """Queue [lo, hi) for the post-replay re-screen drain."""
            ub, ul, uv, cu, plo, phi = desc
            return (
                ub, ul, uv, cu,
                jnp.where(on, jnp.minimum(plo, lo), plo),
                jnp.where(on, jnp.maximum(phi, hi), phi),
            )

        def desc_append_run(desc, on, base, ln, val):
            """One op: rows [base, base+ln) all take verdict row `val`
            (ln <= UWO). Falls back to pending when the op budget is
            full."""
            ub, ul, uv, cu, plo, phi = desc
            w = on & (cu < SU)
            cuc = jnp.minimum(cu, SU - 1)
            ub = ub.at[cuc].set(jnp.where(w, base, ub[cuc]))
            ul = ul.at[cuc].set(jnp.where(w, ln, ul[cuc]))
            uv = uv.at[cuc].set(jnp.where(w, val, uv[cuc]))
            desc = (ub, ul, uv, cu + jnp.where(w, 1, 0), plo, phi)
            return desc_pend(desc, on & ~w, base, base + ln)

        def desc_append_rows(desc, on, rows, vals, k, lo, hi):
            """k single-row ops (rows [UWB], vals [UWB, C]); [lo, hi) is
            the covering interval used when the op budget overflows."""
            ub, ul, uv, cu, plo, phi = desc
            w = on & ((cu + k) <= SU)
            idx = cu + jnp.arange(UWB)
            live = (jnp.arange(UWB) < k) & w
            iw = jnp.where(live, jnp.minimum(idx, SU - 1), SU)  # OOB drops
            ub = ub.at[iw].set(rows)
            ul = ul.at[iw].set(jnp.ones(UWB, jnp.int32))
            uv = uv.at[iw].set(vals)
            desc = (ub, ul, uv, cu + jnp.where(w, k, 0), plo, phi)
            return desc_pend(desc, on & ~w, lo, hi)

        def apply_refresh(screen, desc, state):
            """Replay the step's refresh ops onto the verdict tensor, then
            drain the pending interval. Runs at step level, OUTSIDE the
            valid/skip cond; every write is a blended dynamic-update-slice
            so the scan-carried tensor keeps aliasing in place."""
            ub, ul, uv, cu, plo, phi = desc

            def a_cond(c):
                return c[1] < cu

            def a_body(c):
                scr, e = c
                base, ln, val = ub[e], ul[e], uv[e]
                start = jnp.clip(base, 0, N - UWO)
                idx = start + jnp.arange(UWO)
                in_rng = (idx >= base) & (idx < base + ln)
                win = jax.lax.dynamic_slice(
                    scr, (start, jnp.int32(0)), (UWO, C)
                )
                new = jnp.where(in_rng[:, None], val[None, :], win)
                return (
                    jax.lax.dynamic_update_slice(
                        scr, new, (start, jnp.int32(0))
                    ),
                    e + 1,
                )

            screen, _ = jax.lax.while_loop(
                a_cond, a_body, (screen, jnp.int32(0))
            )

            def d_cond(c):
                return c[1] < c[2]

            def d_body(c):
                scr, lo, hi = c
                start = jnp.clip(lo, 0, N - DW)
                idx = start + jnp.arange(DW)
                gi = jnp.minimum(idx, N - 1)
                blk = screen_ops.rows_vs_items(
                    items_pl, state.allow[gi], state.out[gi],
                    state.defined[gi],
                )  # [DW, I]
                win = jax.lax.dynamic_slice(
                    scr, (start, jnp.int32(0)), (DW, C)
                )
                new = jnp.where(
                    ((idx >= lo) & (idx < hi))[:, None], blk, win
                )
                return (
                    jax.lax.dynamic_update_slice(
                        scr, new, (start, jnp.int32(0))
                    ),
                    lo + DW,
                    hi,
                )

            screen, _, _ = jax.lax.while_loop(
                d_cond, d_body, (screen, plo, phi)
            )
            return screen

        def log_ok(ptr):
            """Commit gate: log space when logging, always-true otherwise."""
            return (ptr < L) if log_commits else jnp.bool_(True)

        def log_write(log, ptr, do, item_i, slot_lo, ns, k, k_last):
            if not log_commits:
                return log, ptr
            p = jnp.minimum(ptr, L - 1)
            w = do & (ptr < L)

            def wr(a, v):
                return a.at[p].set(jnp.where(w, v, a[p]))

            log = {
                **log,
                "item": wr(log["item"], item_i),
                "slot": wr(log["slot"], slot_lo),
                "ns": wr(log["ns"], ns),
                "k": wr(log["k"], k),
                "k_last": wr(log["k_last"], k_last),
            }
            return log, ptr + jnp.where(w, 1, 0)

        def step(carry, x):
            # per-item rows arrive as scan xs (NOT manual indexing by the
            # counter): xs slicing lets the TPU pipeliner double-buffer the
            # row loads, where body-internal dynamic-slices serialized a
            # ~170us alternate-memory copy per row per step (~340ms/solve at
            # 1k items, measured). Padded / empty items skip the whole step
            # body (screens, probes, spread plans) through ONE cond.
            valid_i = x["valid"] & (x["count"] > 0)
            if prescreen and screen_frozen:
                # read-only tensor: gather the column from the scan
                # CONSTANT; no refresh replay, no tensor in the carry —
                # position 3 of the carry is a dead scalar. Opened MACHINE
                # rows are the one place the constant is stale (the
                # sequential path overwrites them at open time): a neutral
                # open writes exactly the template's precomputed row, so
                # read tmpl_rows[state.tmpl] there instead. Unopened
                # machine rows keep the virgin value, which — as in the
                # sequential tensor — is never read (screens AND with
                # state.open).
                def _skip_f(c, _x):
                    return c

                st0 = carry[0]
                vrow0 = jnp.where(
                    st0.open & ~st0.is_existing,
                    tmpl_rows[st0.tmpl, x["scls"]],
                    screen_init[:, x["scls"]],
                )
                state2, log2, ptr2, _ = jax.lax.cond(
                    valid_i, _step_body, _skip_f,
                    (carry[0], carry[1], carry[2], vrow0), x,
                )
                return (state2, log2, ptr2, carry[3]), None
            if prescreen:
                # the step body READS the verdict tensor (one column
                # gather) but returns a refresh descriptor in its place;
                # the tensor is updated here, outside the valid/skip cond,
                # so the scan carry keeps aliasing it (any write under the
                # cond copies the whole tensor per step)
                def _skip(c, _x):
                    return (c[0], c[1], c[2], empty_desc())

                # the verdict-column gather ALSO stays outside the cond:
                # with no read of the tensor anywhere under the cond, its
                # uses form a linear gather -> replay-write chain and XLA
                # aliases the scan carry instead of copying it every step
                vrow = carry[3][:, x["scls"]]
                state2, log2, ptr2, desc = jax.lax.cond(
                    valid_i, _step_body, _skip,
                    (carry[0], carry[1], carry[2], vrow), x,
                )
                screen2 = apply_refresh(carry[3], desc, state2)
                return (state2, log2, ptr2, screen2), None
            return jax.lax.cond(valid_i, _step_body, lambda c, _x: c, carry, x), None

        def _step_body(carry, x):
            # position 3: this item's pre-gathered verdict column [N] in
            # prescreen mode (the tensor itself never enters the step
            # cond), the carried screen placeholder in tiered mode
            state, log, ptr, aux3 = carry
            i = x["i"]
            prow = {
                k: x[k]
                for k in (
                    "allow", "out", "defined", "escape", "custom_deny",
                    "requests", "ports", "port_conflict", "vols",
                )
            }
            # a pod with host ports can never run two replicas on one node
            # (its own entries conflict, hostportusage.go:42-54)
            port_k_cap = (
                jnp.where(prow["ports"].any(), 1, BIGK) if Q else jnp.int32(BIGK)
            )
            if has_topo:
                prow["topo_own"] = x["topo_own"]
                prow["topo_sel"] = x["topo_sel"]
            # item-invariant: does ANY topology group own/select this item?
            # Gates the per-group narrowing in verify/open/bulk — the
            # dominant topology-free items skip that work entirely
            any_topo_i = jnp.bool_(False)
            if has_topo:
                for g in range(len(topo_meta.groups)):
                    any_topo_i |= prow["topo_own"][g] | prow["topo_sel"][g]
            valid = x["valid"]
            count = x["count"]
            # prescreen: this item's verdict column, in sync with the slot
            # planes (every commit refreshes what it wrote). plane_mut
            # gates the refreshes: an item with no defined keys and no
            # topology involvement merges as the identity on
            # allow/out/defined (encode gives undefined keys
            # allow=all/out=True/defined=False), so its commits cannot
            # change any verdict — the dominant generic items skip the
            # re-screen matmuls entirely. Both tests are restricted to
            # SCREENED keys: narrowing an elided hostname key (hostname
            # spread/anti topology) is equally verdict-neutral, which
            # spares the biggest per-slot committers the re-screens.
            if prescreen and screen_frozen:
                # every lane item is plane-neutral (partitioner invariant):
                # no refresh bookkeeping at all
                vrow = aux3
                plane_mut = None
            elif prescreen:
                vrow = aux3  # verdict column [N], gathered by `step`
                any_topo_scr = jnp.bool_(False)
                if has_topo:
                    for g, gm in enumerate(topo_meta.groups):
                        if gm.seg[0] < WSCR:
                            any_topo_scr |= (
                                prow["topo_own"][g] | prow["topo_sel"][g]
                            )
                plane_mut = (prow["defined"] & key_scr).any() | any_topo_scr
            else:
                vrow = None
                plane_mut = None

            # -- screen (once per item), TIERED by nopen ------------------
            # slots at or beyond nopen can never be open, so the [N]-wide
            # screen work (matmuls, fits, topology, ranking) runs on the
            # smallest power-of-two-ish prefix covering the open slots;
            # uncovered tail slots pad to screen=False / score=BIG, which
            # is exactly what the full computation yields for closed slots
            def _screen_upto(limit):
                tol_l = x["tol"][state.tol_idx[:limit]]
                fit_l = compat.fits(
                    state.used[:limit] + prow["requests"][None, :],
                    state.cap[:limit],
                )
                if prescreen:
                    # the screen left the loop body: one [N]-row gather
                    req_l = vrow[:limit]
                else:
                    req_l = slot_compat_screen(
                        state.allow[:limit], state.out[:limit],
                        state.defined[:limit], prow,
                    )
                sc = state.open[:limit] & tol_l & fit_l & req_l
                if has_topo:
                    sc &= topo.topo_screen(
                        topo_meta, state.tcounts, state.thost[:, :limit],
                        state.tdoms, prow["topo_own"], prow["topo_sel"],
                        prow["allow"], state.allow[:limit],
                    )
                if Q:
                    # host-port conflicts (machine.go:69, existingnode.go:77)
                    sc &= ~jnp.any(
                        state.ports[:limit] & prow["port_conflict"][None, :],
                        axis=-1,
                    )
                if W:
                    # CSI volume limits on existing slots
                    # (existingnode.go:62-115): per-driver mounted count +
                    # NEW claims <= CSINode limit; tiers never cut below EV
                    cnt_d = state.vols.astype(jnp.float32) @ vol_driver
                    new = prow["vols"][None, :] & ~state.vols
                    new_d = new.astype(jnp.float32) @ vol_driver
                    vol_ok = jnp.all(cnt_d + new_d <= vol_limits, axis=-1)
                    sc = sc.at[:EV].set(sc[:EV] & vol_ok)
                # rank: existing first by index, then machines by
                # (pods, index)
                idx_l = jnp.arange(limit, dtype=jnp.float32)
                s0 = jnp.where(
                    state.is_existing[:limit],
                    idx_l,
                    jnp.float32(N)
                    + state.pods[:limit].astype(jnp.float32) * N
                    + idx_l,
                )
                s0 = jnp.where(sc, s0, BIG)
                pad = N - limit
                if pad:
                    s0 = jnp.pad(s0, (0, pad), constant_values=BIG)
                return s0

            tiers = sorted(
                {max(EV, (N + 3) // 4), max(EV, (N + 1) // 2),
                 max(EV, (3 * N + 3) // 4), N}
            )
            if N > 2048 and len(tiers) > 1:
                cuts = jnp.array(tiers[:-1], jnp.int32)
                tier_idx = (state.nopen > cuts).sum()
                score0 = jax.lax.switch(
                    tier_idx,
                    [lambda _, t=t: _screen_upto(t) for t in tiers],
                    None,
                )
            else:
                score0 = _screen_upto(N)

            f_static_p = x["f_static"]  # [J, T]
            openable_p = x["openable"]  # [J]

            owns_vk_spread0 = jnp.bool_(False)
            for g, _gm in vk_spread_gs:
                owns_vk_spread0 |= prow["topo_own"][g]

            # per-domain open-feasibility probes are loop-invariant for the
            # item: compute once per step, consult every iteration — gated
            # behind ownership so the (dominant) non-spread items skip the
            # J x T x seg probe work entirely
            def _compute_dom_open(_):
                out = []
                for g, gm in vk_spread_gs:
                    out.append(_dom_open_one(g, gm))
                return tuple(out)

            def _zeros_dom_open(_):
                return tuple(
                    jnp.zeros(gm.seg[1] - gm.seg[0], dtype=bool)
                    for _g, gm in vk_spread_gs
                )

            def _dom_open_one(g, gm):
                lo, hi = gm.seg
                dom_open = jnp.zeros(hi - lo, dtype=bool)
                for j in range(J):
                    f_j = f_static_p[j] & tmpl_type_mask[j]  # [T]
                    type_dom = type_reqs["allow"][:, lo:hi]  # [T, seg]
                    if (lo, hi) == (zlo, zhi):
                        # zone spread: a zone is only openable if some type
                        # has an AVAILABLE offering there for the merged
                        # capacity types (types list unavailable zones in
                        # their requirements too)
                        ct_allow = (
                            tmpl_reqs["allow"][j, clo:chi]
                            & prow["allow"][clo:chi]
                        )
                        type_zone_ok = (
                            jnp.einsum(
                                "tzc,c->tz",
                                type_offering_ok.astype(jnp.float32),
                                ct_allow.astype(jnp.float32),
                            )
                            > 0.5
                        )
                        type_dom = type_dom & type_zone_ok
                    dom_open |= (
                        openable_p[j]
                        & tmpl_reqs["allow"][j, lo:hi]
                        & (f_j[:, None] & type_dom).any(axis=0)
                    )
                return dom_open

            if vk_spread_gs:
                dom_open_t = jax.lax.cond(
                    owns_vk_spread0, _compute_dom_open, _zeros_dom_open, None
                )
                dom_open_by_g = {
                    g: dom_open_t[x] for x, (g, _gm) in enumerate(vk_spread_gs)
                }
            else:
                dom_open_by_g = {}

            def spread_plan(state, remaining, dead, score, ptr):
                """Per-iteration water-fill targeting for owned value-key
                spread groups: pick the argmin-count LIVE domain d* and cap
                the commit at the final fill level minus d*'s count (the bulk
                equivalent of greedy's per-pod argmin choice,
                topologygroup.go:155-182).

                A domain is live when it is still placeable: a current
                candidate slot allows it or a fresh machine could open in it
                (the per-item probe above). Infeasible and retired domains
                are FROZEN: their counts stop growing, so — exactly like the
                reference's skew rule, where the global min pins every other
                domain to min+maxSkew — commits into live domains are
                additionally bounded by min(frozen counts) + max_skew.

                The probe cannot see resource-coupled budgets (provisioner
                limits, the slot budget, log space): a sibling domain can
                turn out infeasible only after this one consumed the budget.
                When any such budget is scarce the plan DEGRADES to the
                per-pod skew bound against the min over ALL pod domains —
                small, reference-faithful commits that can never overfill a
                domain whose siblings later fail.

                Returns (force[V] domain mask, cap, blocked, gate[N] slots
                allowing d*, dmark[V] domains to retire if placement in d*
                proves impossible)."""
                force = jnp.ones(V, dtype=bool)
                cap = BIGK
                blocked = jnp.bool_(False)
                gate = jnp.ones(N, dtype=bool)
                dmark = jnp.zeros(V, dtype=bool)
                cands = score < BIG
                limits_finite = (state.remaining < jnp.float32(1e29)).any()
                # open-feasibility is only statically provable when the vk
                # spread is the item's SOLE structural constraint: an item
                # that also owns hostname-affinity/anti groups (s capped to 1,
                # opens gated on co-location) or owns >1 vk-spread group
                # (joint domain feasibility) can fail inside do_open AFTER
                # the bulk commit, leaving a domain irreversibly above
                # min(frozen)+max_skew. Those items degrade to the per-pod
                # skew bound (minc_all), like the reference's per-pod loop.
                vk_ids = {g for g, _ in vk_spread_gs}
                n_owned_vk_p = jnp.int32(0)
                for g, _gm in vk_spread_gs:
                    n_owned_vk_p += prow["topo_own"][g].astype(jnp.int32)
                owns_nonspread = jnp.bool_(False)
                for g in range(len(topo_meta.groups) if has_topo else 0):
                    if g not in vk_ids:
                        owns_nonspread |= prow["topo_own"][g]
                not_provable = (n_owned_vk_p > 1) | owns_nonspread
                for g, gm in vk_spread_gs:
                    applies = prow["topo_own"][g]
                    lo, hi = gm.seg
                    pod_dom = prow["allow"][lo:hi] & state.tdoms[g, lo:hi]
                    dom_cand = (cands[:, None] & state.allow[:, lo:hi]).any(axis=0)
                    live = pod_dom & ~dead[lo:hi] & (dom_cand | dom_open_by_g[g])
                    frozen = pod_dom & ~live
                    cnt = state.tcounts[g, lo:hi]
                    minc_frozen = jnp.min(
                        jnp.where(frozen, cnt, jnp.inf), initial=jnp.inf
                    )
                    minc_all = jnp.min(
                        jnp.where(pod_dom, cnt, jnp.inf), initial=jnp.inf
                    )
                    n_live = live.sum()
                    degraded = (
                        limits_finite
                        | ((N - state.nopen) < n_live)
                        | ((L - ptr) < n_live + 1)
                        | not_provable
                    )
                    level = (
                        jnp.where(live, cnt, 0.0).sum()
                        + remaining.astype(jnp.float32)
                    ) / jnp.maximum(n_live, 1).astype(jnp.float32)
                    cntm = jnp.where(live, cnt, jnp.inf)
                    d_star = jnp.argmin(cntm)
                    has_live = live.any()
                    level_cap = jnp.maximum(jnp.floor(level - cntm[d_star]), 1.0)
                    skew_cap = jnp.where(
                        degraded,
                        minc_all + jnp.float32(gm.max_skew) - cntm[d_star],
                        minc_frozen + jnp.float32(gm.max_skew) - cntm[d_star],
                    )
                    cap_f = jnp.minimum(level_cap, skew_cap)
                    skew_blocked = has_live & (cap_f < 1.0)
                    cap_g = jnp.where(
                        skew_blocked | ~has_live,
                        0,
                        jnp.clip(cap_f, 1.0, jnp.float32(BIGK)).astype(jnp.int32),
                    )
                    oh = (jnp.arange(hi - lo) == d_star) & has_live
                    force = force.at[lo:hi].set(
                        jnp.where(applies, oh, force[lo:hi])
                    )
                    dmark = dmark.at[lo:hi].set(
                        jnp.where(applies, oh, dmark[lo:hi])
                    )
                    cap = jnp.where(applies, jnp.minimum(cap, cap_g), cap)
                    blocked |= applies & (~has_live | skew_blocked)
                    gate &= jnp.where(applies, state.allow[:, lo + d_star], True)
                return force, cap, blocked, gate, dmark

            owns_vk_spread = jnp.bool_(False)
            n_owned_vk = jnp.int32(0)
            for g, _gm in vk_spread_gs:
                owns_vk_spread |= prow["topo_own"][g]
                n_owned_vk += prow["topo_own"][g].astype(jnp.int32)

            # bulk existing-fill eligibility is loop-invariant per item
            if EB > 0 and has_topo:
                item_bulk_ok = topo.topo_bulk_item_ok(
                    topo_meta, prow["topo_own"], prow["topo_sel"]
                )
            else:
                item_bulk_ok = jnp.bool_(EB > 0)
            # machine-region bulk eligibility: every group involving the
            # item must be slot-local (hostname anti/inverse) or
            # recording-only — see topo_mach_bulk_item_ok
            if mach_bulk:
                mach_ok_i = topo.topo_mach_bulk_item_ok(
                    topo_meta, prow["topo_own"], prow["topo_sel"]
                )
            else:
                mach_ok_i = jnp.bool_(False)

            # -- candidate branch: verify best slot, commit k replicas ----
            def do_candidate(args):
                carry, force, cap, gate, _dmark = args
                # scrd: refresh descriptor in prescreen mode (see
                # empty_desc), dead screen placeholder in tiered mode
                state, log, ptr, remaining, score, _, dead, scrd = carry
                n = jnp.argmin(jnp.where(gate, score, BIG))
                ok, compat_tmask, kcap_t, kmax, narrow, applied_keys = verify_slot(
                    state, prow, n, type_reqs, type_alloc, type_offering_ok,
                    f_static_p, spread_force=force if has_topo else None,
                    any_topo=any_topo_i if has_topo else None,
                )
                k = jnp.minimum(jnp.minimum(remaining, kmax), cap)
                do = ok & (k >= 1) & log_ok(ptr)

                m_allow = state.allow[n] & prow["allow"] & narrow
                m_out = state.out[n] & prow["out"] & ~applied_keys
                m_defined = state.defined[n] | prow["defined"] | applied_keys
                is_existing = state.is_existing[n]
                new_used = state.used[n] + k.astype(jnp.float32) * prow["requests"]
                tmask_k = compat_tmask & (kcap_t >= k)
                new_tmask = jnp.where(is_existing, state.tmask[n], tmask_k)
                new_cap = jnp.where(
                    is_existing, state.cap[n], _segment_max_alloc(tmask_k, type_alloc)
                )
                onehot = jnp.arange(N) == n

                # commit UNCONDITIONALLY with predicated row values: a
                # lax.cond(do, apply, id) here made XLA copy the whole
                # [N, V]/[N, T] planes on every taken branch to unify branch
                # buffers (~80ms/solve at 50k); a no-op row write aliases
                def row(new, old):
                    return jnp.where(do, new, old)

                state = state._replace(
                    used=state.used.at[n].set(row(new_used, state.used[n])),
                    pods=state.pods.at[n].add(jnp.where(do, k, 0)),
                    allow=state.allow.at[n].set(row(m_allow, state.allow[n])),
                    out=state.out.at[n].set(row(m_out, state.out[n])),
                    defined=state.defined.at[n].set(row(m_defined, state.defined[n])),
                    tmask=state.tmask.at[n].set(row(new_tmask, state.tmask[n])),
                    cap=state.cap.at[n].set(row(new_cap, state.cap[n])),
                )
                if Q:
                    state = state._replace(
                        ports=state.ports.at[n].set(
                            row(state.ports[n] | prow["ports"], state.ports[n])
                        )
                    )
                if W:
                    ne = jnp.minimum(n, EV - 1)
                    nv = jnp.where(
                        do & (n < EV), state.vols[ne] | prow["vols"], state.vols[ne]
                    )
                    state = state._replace(vols=state.vols.at[ne].set(nv))
                # record_topo is a strict no-op when the masked k_row is all
                # zero (topo_record gates domain registration on placement)
                state = record_topo(
                    state, prow, m_allow, m_out, m_defined, well_known, topo_terms,
                    onehot & do, jnp.where(onehot & do, k, 0),
                )
                log, ptr = log_write(log, ptr, do, i, n, 1, k, k)
                remaining = remaining - jnp.where(do, k, 0)
                if prescreen and not screen_frozen:
                    # incremental refresh: re-screen ONLY slot row n (post-
                    # commit planes) against the whole item axis, recorded
                    # as one descriptor op — `step` replays it outside the
                    # cond tree (see empty_desc). Skipped via the cond when
                    # the commit cannot have changed the planes (no-op
                    # merge) or didn't happen — the branches carry one [C]
                    # row, not the tensor.
                    col_on = plane_mut & do

                    def _refresh_col(_):
                        return screen_ops.items_vs_row(
                            items_pl, state.allow[n], state.out[n],
                            state.defined[n],
                        )

                    col = jax.lax.cond(
                        col_on, _refresh_col,
                        lambda _: jnp.zeros(C, dtype=bool), None,
                    )
                    scrd = desc_append_run(
                        scrd, col_on, n, jnp.int32(1), col
                    )
                # retire the slot on failure or when filled to capacity; a
                # commit limited only by the water-fill cap leaves the slot
                # available for a later fill round in the same domain
                retire = (~do) | (k >= kmax)
                score = score.at[n].set(jnp.where(retire, BIG, score[n]))
                return state, log, ptr, remaining, score, jnp.bool_(False), dead, scrd

            # -- bulk fill: ALL gated candidates in one iteration (the
            # reference tries existing nodes in index order per pod,
            # scheduler.go:179-185 — identical replicas filling in index
            # order under per-slot caps reproduce it exactly). Without this,
            # a 1000-node cluster costs one while-iteration per slot per
            # item. With mach_bulk the region widens to the full slot axis
            # and takes follow the score order (existing first by index,
            # then machines ascending pod count — the do_candidate order),
            # with exact per-slot type narrowing for machine rows.
            def do_bulk(args):
                # every tensor here is restricted to the bulk region [:BR] —
                # the existing prefix unless the geometry admits machine-
                # region bulk items; a machine-slot tail would otherwise
                # multiply every op's cost ~N/EB-fold for nothing
                carry, force, cap, gate, _dmark = args
                state, log, ptr, remaining, score, _, dead, scrd = carry
                sa = state.allow[:BR]
                cands = (score[:BR] < BIG) & gate[:BR] & (
                    state.is_existing[:BR]
                    if not mach_bulk
                    else (state.is_existing[:BR] | mach_ok_i)
                )
                if has_topo:
                    # topology-free items (the bulk of a real batch) skip the
                    # whole group evaluation through one cond
                    any_topo = any_topo_i
                    thost_e = state.thost[:, :BR] if has_topo else None

                    def topo_eval(_):
                        viable = topo.topo_screen(
                            topo_meta, state.tcounts, thost_e, state.tdoms,
                            prow["topo_own"], prow["topo_sel"], prow["allow"],
                            sa,
                        )
                        narrow, applied_keys, k_topo_e = topo.topo_bulk_narrow(
                            topo_meta, state.tcounts, thost_e, state.tdoms,
                            prow["topo_own"], prow["topo_sel"], prow["allow"], K,
                            spread_force=force,
                        )
                        # owned narrowed domains must stay reachable per slot
                        for g, gm in enumerate(topo_meta.groups):
                            if gm.is_hostname or gm.is_inverse:
                                continue
                            if gm.gtype in (topo.TOPO_SPREAD, topo.TOPO_AFFINITY):
                                lo, hi = gm.seg
                                ok_g = (sa[:, lo:hi] & narrow[lo:hi]).any(-1)
                                viable &= ~prow["topo_own"][g] | ok_g
                        return viable, narrow, applied_keys, k_topo_e

                    def topo_skip(_):
                        return (
                            jnp.ones(BR, dtype=bool),
                            jnp.ones(V, dtype=bool),
                            jnp.zeros(K, dtype=bool),
                            jnp.full(BR, BIGK, dtype=jnp.int32),
                        )

                    viable, narrow, applied_keys, k_topo_e = jax.lax.cond(
                        any_topo, topo_eval, topo_skip, None
                    )
                else:
                    viable = jnp.ones(BR, dtype=bool)
                    narrow = jnp.ones(V, dtype=bool)
                    applied_keys = jnp.zeros(K, dtype=bool)
                    k_topo_e = jnp.full(BR, BIGK, dtype=jnp.int32)

                m_allow_rows = sa & (prow["allow"] & narrow)[None, :]
                m_out_rows = state.out[:BR] & prow["out"][None, :] & ~applied_keys[None, :]
                m_def_rows = (
                    state.defined[:BR] | prow["defined"][None, :] | applied_keys[None, :]
                )

                # existing-prefix capacity only when mach_bulk (the machine
                # tail gets exact per-type caps below; computing k_e over it
                # would be dead work every iteration)
                KEW = EB if mach_bulk else BR
                k_e = replica_cap(
                    state.cap[:KEW], state.used[:KEW], prow["requests"]
                )  # [KEW]
                if mach_bulk:
                    # exact surviving-type computation for MACHINE rows only
                    # — the bulk analog of verify_slot (merged_types_compat +
                    # per-type replica caps, machine.go:137-159), vectorized
                    # over the static machine slice [EB, BR): the existing
                    # prefix keeps its fixed-capacity k_e and would discard
                    # these rows anyway. Gated behind eligibility so pure
                    # existing-prefix fills skip the [MBW, T] work entirely.
                    MBW = BR - EB

                    def _mach_rows(_):
                        tmask_c = mach_rows_types_compat(
                            m_allow_rows[EB:], m_out_rows[EB:],
                            m_def_rows[EB:],
                            state.tmask[EB:BR]
                            & f_static_p[state.tmpl[EB:BR]],
                            type_reqs, type_offering_ok,
                        )
                        kcap_r = replica_cap_rows(
                            type_alloc, state.used[EB:BR], prow["requests"]
                        )
                        return tmask_c, kcap_r

                    def _mach_skip(_):
                        T = type_alloc.shape[0]
                        return (
                            jnp.zeros((MBW, T), dtype=bool),
                            jnp.zeros((MBW, T), dtype=jnp.int32),
                        )

                    tmask_rows, kcap_rows = jax.lax.cond(
                        mach_ok_i, _mach_rows, _mach_skip, None
                    )
                    k_mach = jnp.max(
                        jnp.where(tmask_rows, kcap_rows, 0), axis=-1
                    )  # [MBW]
                    k_slot = jnp.concatenate([k_e, k_mach])
                else:
                    k_slot = k_e
                k_eff = jnp.where(
                    cands & viable, jnp.minimum(k_slot, k_topo_e), 0
                )
                k_eff = jnp.minimum(k_eff, port_k_cap)
                budget = jnp.minimum(remaining, cap)
                if mach_bulk:
                    # take in score order (existing slots rank below machine
                    # slots by construction) so a budget smaller than the
                    # candidate capacity lands on the same slots the
                    # sequential do_candidate loop would have filled
                    order = jnp.argsort(jnp.where(k_eff > 0, score[:BR], BIG))
                    k_ord = k_eff[order]
                    csum_o = jnp.cumsum(k_ord)
                    take_o = jnp.clip(budget - (csum_o - k_ord), 0, k_ord)
                    take = jnp.zeros_like(k_eff).at[order].set(take_o)
                else:
                    csum = jnp.cumsum(k_eff)
                    take = jnp.clip(budget - (csum - k_eff), 0, k_eff)
                placed = take.sum()
                bn = log["bulk_n"]
                do = (placed >= 1) & log_ok(ptr) & (
                    (bn < LB) if log_commits else jnp.bool_(True)
                )

                # unconditional commit with do-predicated takes (see
                # do_candidate: a state-carrying lax.cond copies the planes)
                take = jnp.where(do, take, 0)
                touched = take > 0
                tm = touched[:, None]
                state = state._replace(
                    used=state.used.at[:BR].set(
                        state.used[:BR]
                        + take[:, None].astype(jnp.float32)
                        * prow["requests"][None, :]
                    ),
                    pods=state.pods.at[:BR].add(take),
                    allow=state.allow.at[:BR].set(
                        jnp.where(tm, m_allow_rows, sa)
                    ),
                    out=state.out.at[:BR].set(
                        jnp.where(tm, m_out_rows, state.out[:BR])
                    ),
                    defined=state.defined.at[:BR].set(
                        jnp.where(tm, m_def_rows, state.defined[:BR])
                    ),
                )
                if mach_bulk:
                    # touched machine rows narrow their surviving types to
                    # those that fit the committed replicas (tmask_k =
                    # compat ∧ kcap >= k, as in do_candidate) and refresh
                    # the optimistic capacity; the existing prefix never
                    # narrows types, so the writes cover [EB, BR) only
                    tmm = touched[EB:][:, None]
                    new_tmask_rows = tmask_rows & (
                        kcap_rows >= take[EB:, None]
                    )
                    state = state._replace(
                        tmask=state.tmask.at[EB:BR].set(
                            jnp.where(tmm, new_tmask_rows, state.tmask[EB:BR])
                        ),
                        cap=state.cap.at[EB:BR].set(
                            jnp.where(
                                tmm,
                                _segment_max_alloc(new_tmask_rows, type_alloc),
                                state.cap[EB:BR],
                            )
                        ),
                    )
                if Q:
                    state = state._replace(
                        ports=state.ports.at[:BR].set(
                            jnp.where(
                                tm, state.ports[:BR] | prow["ports"][None, :],
                                state.ports[:BR],
                            )
                        )
                    )
                if W:
                    EVB = min(EV, BR)
                    state = state._replace(
                        vols=state.vols.at[:EVB].set(
                            jnp.where(
                                tm[:EVB],
                                state.vols[:EVB] | prow["vols"][None, :],
                                state.vols[:EVB],
                            )
                        )
                    )
                if has_topo:
                    # topo_record_bulk is a strict no-op at take==0; the cond
                    # carries only the small count tensors
                    def rec(args):
                        tc, th, td = topo.topo_record_bulk(
                            topo_meta, *args,
                            prow["topo_own"], prow["topo_sel"],
                            m_allow_rows, m_out_rows, take,
                        )
                        return tc, th, td

                    tcounts, thost, tdoms = jax.lax.cond(
                        any_topo, rec, lambda a: a,
                        (state.tcounts, state.thost, state.tdoms),
                    )
                    state = state._replace(tcounts=tcounts, thost=thost, tdoms=tdoms)
                if log_commits:
                    bslot = jnp.minimum(bn, LB - 1)
                    log = {
                        **log,
                        "bulk_take": log["bulk_take"].at[bslot].set(
                            jnp.where(do, take, log["bulk_take"][bslot])
                        ),
                        "bulk_n": bn + jnp.where(do, 1, 0),
                    }
                log, ptr = log_write(log, ptr, do, i, 0, -1, bn, placed)
                remaining = remaining - jnp.where(do, placed, 0)
                if prescreen and not screen_frozen:
                    # only TOUCHED rows changed planes (each merged with
                    # this item's planes) — a bulk commit touches at most
                    # the item's replica count of rows, so gather up to UWB
                    # of them, re-screen that small block, and record the
                    # rows as descriptor ops (`step` replays them outside
                    # the cond tree). A commit touching > UWB rows queues
                    # the covering interval [first touched, last touched+1)
                    # onto the pending drain instead — re-screening the
                    # untouched rows in between is exact, just redundant.
                    # Plane-neutral items skip everything through the cond.
                    bulk_on = plane_mut & do
                    ntouched = touched.sum()
                    over = ntouched > UWB
                    # stable argsort of ~touched: touched indices first, in
                    # index order
                    tidx = jnp.argsort(~touched)[:UWB]
                    gidx = jnp.where(jnp.arange(UWB) < ntouched, tidx, 0)

                    def _chunk(_):
                        return screen_ops.rows_vs_items(
                            items_pl, state.allow[gidx], state.out[gidx],
                            state.defined[gidx],
                        )  # [UWB, C]

                    blk = jax.lax.cond(
                        bulk_on & ~over, _chunk,
                        lambda _: jnp.zeros((UWB, C), dtype=bool), None,
                    )
                    t_lo = jnp.argmax(touched).astype(jnp.int32)
                    t_hi = (
                        jnp.int32(BR)
                        - jnp.argmax(touched[::-1]).astype(jnp.int32)
                    )
                    scrd = desc_append_rows(
                        scrd, bulk_on & ~over, tidx, blk,
                        ntouched.astype(jnp.int32), t_lo, t_hi,
                    )
                    scrd = desc_pend(scrd, bulk_on & over, t_lo, t_hi)
                # retire filled/unusable slots; on a no-op pass retire every
                # candidate so the loop is guaranteed to progress
                retire = cands & jnp.where(do, (k_eff == 0) | (take >= k_eff), True)
                score = score.at[:BR].set(jnp.where(retire, BIG, score[:BR]))
                carry2 = (state, log, ptr, remaining, score, jnp.bool_(False), dead,
                          scrd)
                # fused open: when the exist fill leaves no candidate at all
                # and the item owns no vk-spread (whose per-round cap must be
                # re-planned), open fresh machines in the SAME iteration —
                # the common topology-free item packs in ONE iteration
                # instead of bulk + open
                exist_left = ((score < BIG) & gate & state.is_existing).any()
                mach_cand = ((score < BIG) & gate & ~state.is_existing).any()
                need_open = (
                    do & ~exist_left & ~mach_cand & (remaining > 0)
                    & ~owns_vk_spread0
                )
                carry2 = jax.lax.cond(
                    need_open,
                    lambda c: open_commit(c, force, cap, _dmark),
                    lambda c: c,
                    carry2,
                )
                return carry2

            # -- open branch: bulk-open s fresh slots, m replicas each ----
            def do_open(args):
                carry, force, cap, _gate, dmark = args
                return open_commit(carry, force, cap, dmark)

            def open_commit(carry, force, cap, dmark):
                state, log, ptr, remaining, score, _, dead, scrd = carry
                cap_ok = jnp.all(
                    type_capacity[None, :, :] <= state.remaining[:, None, :], axis=-1
                )  # [J, T]
                viab, allows, outs, defs, compats, kcaps, ktopos = (
                    [], [], [], [], [], [], []
                )
                for j in range(J):  # static unroll — J is the provisioner count
                    fresh_allow = tmpl_reqs["allow"][j]
                    if has_topo:
                        # gated on the item-invariant any_topo flag: the
                        # dominant topology-free items skip the per-group
                        # narrowing on every (fused) open
                        def _narrow_j(_, fresh_allow=fresh_allow):
                            return topo.topo_narrow_single(
                                topo_meta, state.tcounts, state.thost,
                                state.tdoms, prow["topo_own"],
                                prow["topo_sel"], prow["allow"],
                                fresh_allow, state.nopen, K,
                                spread_force=force,
                            )

                        tv, tnarrow, tkeys, k_topo_j = jax.lax.cond(
                            any_topo_i, _narrow_j,
                            lambda _: _topo_skip(V, K), None,
                        )
                    else:
                        tv, tnarrow, tkeys, k_topo_j = _topo_skip(V, K)
                    m_allow_j = fresh_allow & prow["allow"] & tnarrow
                    m_out_j = tmpl_reqs["out"][j] & prow["out"] & ~tkeys
                    m_def_j = tmpl_reqs["defined"][j] | prow["defined"] | tkeys
                    compat_j = merged_types_compat(
                        m_allow_j, m_out_j, m_def_j,
                        tmpl_type_mask[j] & cap_ok[j] & f_static_p[j],
                        type_reqs, type_offering_ok,
                    )
                    kcap_j = replica_cap(
                        type_alloc, tmpl_daemon[j][None, :], prow["requests"]
                    )
                    viab.append(tv & (compat_j & (kcap_j >= 1)).any())
                    allows.append(m_allow_j)
                    outs.append(m_out_j)
                    defs.append(m_def_j)
                    compats.append(compat_j)
                    kcaps.append(kcap_j)
                    ktopos.append(k_topo_j)
                can_open_j = jnp.stack(viab) & openable_p  # [J]
                jc = jnp.argmax(can_open_j)
                m_allow_o = jnp.stack(allows)[jc]
                m_out_o = jnp.stack(outs)[jc]
                m_def_o = jnp.stack(defs)[jc]
                compat_o = jnp.stack(compats)[jc]  # [T]
                kcap_o = jnp.stack(kcaps)[jc]  # [T]
                k_topo_o = jnp.stack(ktopos)[jc]

                # per-slot replica cap: capacity ∧ skew headroom ∧ host ports
                m_eff = jnp.minimum(
                    jnp.max(jnp.where(compat_o, kcap_o, 0), initial=0), k_topo_o
                )
                m_eff = jnp.minimum(m_eff, port_k_cap)
                m_eff = jnp.maximum(m_eff, 0)

                # provisioner-limit slot budget via pessimistic max-capacity
                # subtraction over the k>=1 type set (scheduler.go:276-293)
                tmask_1 = compat_o & (kcap_o >= 1)
                max_cap = jnp.where(tmask_1[:, None], type_capacity, -BIG).max(axis=0)
                max_cap = jnp.maximum(max_cap, 0.0)
                lim = state.remaining[jc]  # [R]
                s_lim_r = jnp.where(
                    max_cap > 0, jnp.floor(lim / jnp.where(max_cap > 0, max_cap, 1.0)),
                    jnp.float32(BIGK),
                )
                s_limit = jnp.clip(s_lim_r.min(), 0.0, jnp.float32(BIGK)).astype(jnp.int32)

                # the water-fill cap bounds how much of the item goes to the
                # current forced domain this iteration
                target = jnp.minimum(remaining, cap)
                s_need = (target + jnp.maximum(m_eff, 1) - 1) // jnp.maximum(m_eff, 1)
                s = jnp.minimum(jnp.minimum(s_need, N - state.nopen), s_limit)
                if has_topo:
                    # a hostname-affinity owner's replicas must co-locate on
                    # the seeded host: never bulk-open more than one fresh
                    # slot for it (leftovers fail, as in the reference where
                    # later replicas cannot join a full seeded node)
                    own_hostaff = jnp.bool_(False)
                    for g, gm in enumerate(topo_meta.groups):
                        if (
                            gm.is_hostname
                            and gm.gtype == topo.TOPO_AFFINITY
                            and not gm.is_inverse
                        ):
                            own_hostaff |= prow["topo_own"][g]
                    s = jnp.where(own_hostaff, jnp.minimum(s, 1), s)
                can = can_open_j.any() & (m_eff >= 1) & (s >= 1) & log_ok(ptr)
                s = jnp.where(can, s, 0)

                placed = jnp.minimum(target, s * m_eff)
                k_last = placed - (s - 1) * m_eff
                arange = jnp.arange(N)
                rows = (arange >= state.nopen) & (arange < state.nopen + s)
                last = arange == (state.nopen + s - 1)
                k_row = jnp.where(rows, jnp.where(last, k_last, m_eff), 0)

                tmask_full = compat_o & (kcap_o >= m_eff)
                tmask_last = compat_o & (kcap_o >= k_last)
                cap_full = _segment_max_alloc(tmask_full, type_alloc)
                cap_last = _segment_max_alloc(tmask_last, type_alloc)
                used_rows = (
                    tmpl_daemon[jc][None, :]
                    + k_row[:, None].astype(jnp.float32) * prow["requests"][None, :]
                )

                # unconditional commit: `can=False` already forces s=0, so
                # `rows` is empty and every write below is the identity —
                # the former lax.cond(can, apply, id) cost a full-plane copy
                # per taken branch for buffer unification (see do_candidate)
                rm = rows[:, None]
                lastm = (rows & last)[:, None]
                state = state._replace(
                    used=jnp.where(rm, used_rows, state.used),
                    open=state.open | rows,
                    is_existing=state.is_existing & ~rows,
                    tmpl=jnp.where(rows, jc.astype(jnp.int32), state.tmpl),
                    tol_idx=jnp.where(rows, jc.astype(jnp.int32), state.tol_idx),
                    pods=jnp.where(rows, k_row, state.pods),
                    allow=jnp.where(rm, m_allow_o[None, :], state.allow),
                    out=jnp.where(rm, m_out_o[None, :], state.out),
                    defined=jnp.where(rm, m_def_o[None, :], state.defined),
                    tmask=jnp.where(
                        lastm, tmask_last[None, :],
                        jnp.where(rm, tmask_full[None, :], state.tmask),
                    ),
                    cap=jnp.where(
                        lastm, cap_last[None, :],
                        jnp.where(rm, cap_full[None, :], state.cap),
                    ),
                    nopen=state.nopen + s,
                    remaining=state.remaining
                    - (jnp.arange(J) == jc)[:, None]
                    * s.astype(jnp.float32)
                    * max_cap[None, :],
                )
                if Q:
                    state = state._replace(
                        ports=jnp.where(rm, prow["ports"][None, :], state.ports)
                    )
                state = record_topo(
                    state, prow, m_allow_o, m_out_o, m_def_o, well_known, topo_terms,
                    rows, k_row,
                )
                log, ptr = log_write(log, ptr, can, i, state.nopen - s, s, m_eff, k_last)
                remaining = remaining - jnp.where(can, placed, 0)
                # freshly opened slots become candidates for this item's later
                # fill rounds (e.g. the final water-fill remainder returns to
                # a partially-filled machine instead of opening another)
                score = jnp.where(
                    rows & can,
                    jnp.float32(N) + k_row.astype(jnp.float32) * N + arange,
                    score,
                )
                # a spread owner that cannot place in the forced domain
                # retires it and retries the next argmin domain; only a
                # non-spread item (or one out of domains) is truly stuck
                failed = ~can
                # retire the forced domain only when a SINGLE owned spread
                # group chose it — with several owned groups only the joint
                # combination proved infeasible, and retiring each member
                # would wrongly freeze individually-placeable domains (the
                # reference simply fails such a pod, machine.go:94-107)
                dead = dead | (dmark & failed & (n_owned_vk == 1))
                exhausted = failed & (n_owned_vk != 1)
                if prescreen and not screen_frozen:
                    # every opened slot carries the SAME merged row, so ONE
                    # descriptor op — [base, base+s) sharing one [C]
                    # verdict row — covers the whole open (`step` replays
                    # it outside the cond tree). A plane-neutral non-topo
                    # item merges as the identity, so its row IS the
                    # template's planes and the verdict row is the
                    # precomputed tmpl_cols gather; only plane-mutating
                    # items pay the exact re-screen. Opens wider than UWO
                    # queue [base, base+s) onto the pending drain instead.
                    base = state.nopen - s  # first freshly-opened slot
                    over_o = can & (s > UWO)

                    def _exact_col(_):
                        return screen_ops.items_vs_row(
                            items_pl, m_allow_o, m_out_o, m_def_o
                        )

                    col_o = jax.lax.cond(
                        can & plane_mut, _exact_col,
                        lambda _: tmpl_rows[jc], None,
                    )
                    scrd = desc_append_run(
                        scrd, can & ~over_o, base, s, col_o
                    )
                    scrd = desc_pend(scrd, over_o, base, base + s)
                return state, log, ptr, remaining, score, exhausted, dead, scrd

            def cond_fn(carry):
                remaining, exhausted, tries = carry[3], carry[5], carry[8]
                # backstop only: commits consume `count`, failed verifies
                # retire slots (<= N), open failures retire domains (<= V)
                return (remaining > 0) & (~exhausted) & (tries < count + N + V + 64)

            def body_fn(carry):
                inner = carry[:8]
                tries = carry[8]
                state_c, remaining_c, score_c, dead_c = (
                    carry[0], carry[3], carry[4], carry[6],
                )
                if vk_spread_gs:
                    # non-owners skip the whole water-fill plan (cond, not
                    # where): the plan's [N]/[seg] reductions are per-
                    # iteration costs the dominant topology-free items
                    # shouldn't pay
                    force, cap, blocked, gate, dmark = jax.lax.cond(
                        owns_vk_spread0,
                        lambda _: spread_plan(
                            state_c, remaining_c, dead_c, score_c, carry[2]
                        ),
                        lambda _: (
                            jnp.ones(V, dtype=bool),
                            jnp.int32(BIGK),
                            jnp.bool_(False),
                            jnp.ones(N, dtype=bool),
                            jnp.zeros(V, dtype=bool),
                        ),
                        None,
                    )
                else:
                    force = jnp.ones(V, dtype=bool)
                    cap = BIGK
                    blocked = jnp.bool_(False)
                    gate = jnp.ones(N, dtype=bool)
                    dmark = jnp.zeros(V, dtype=bool)
                has_cand = jnp.where(gate, score_c, BIG).min() < BIG
                args = (inner, force, cap, gate, dmark)
                if BR > 0:
                    exist_cand = (
                        (score_c < BIG) & gate & state_c.is_existing
                    ).any()
                    need_seed = (
                        topo.topo_bulk_need_seed(
                            topo_meta, state_c.tcounts, state_c.tdoms,
                            prow["topo_own"], prow["allow"],
                        )
                        if has_topo
                        else jnp.bool_(False)
                    )
                    bulk_ready = item_bulk_ok & exist_cand
                    if mach_bulk:
                        # machine-region-eligible items bulk whenever ANY
                        # candidate exists (the region covers the full axis)
                        bulk_ready |= mach_ok_i & has_cand
                    use_bulk = (
                        bulk_ready
                        & ~need_seed
                        & ((carry[1]["bulk_n"] < LB) if log_commits
                           else jnp.bool_(True))
                    )
                    inner = jax.lax.cond(
                        use_bulk,
                        do_bulk,
                        lambda a: jax.lax.cond(has_cand, do_candidate, do_open, a),
                        args,
                    )
                else:
                    inner = jax.lax.cond(has_cand, do_candidate, do_open, args)
                (state_n, log_n, ptr_n, remaining_n, score_n, exhausted_n,
                 dead_n, x8) = inner
                return (
                    state_n, log_n, ptr_n, remaining_n, score_n,
                    exhausted_n | blocked, dead_n, x8, tries + 1,
                )

            remaining0 = jnp.where(valid, count, 0)
            # in prescreen mode the while carries the refresh descriptor in
            # the screen's slot; the tensor itself stays outside the step
            # cond and is updated by `step` via apply_refresh
            x8_0 = empty_desc() if (prescreen and not screen_frozen) else aux3
            carry0 = (
                state, log, ptr, remaining0, score0, jnp.bool_(False),
                jnp.zeros(V, dtype=bool), x8_0, jnp.int32(0),
            )
            state, log, ptr, _, _, _, _, x8, _ = jax.lax.while_loop(
                cond_fn, body_fn, carry0
            )
            return (state, log, ptr, x8)

        xs = dict(
            item_arrays,
            # `i` is the id the commit log records per entry: the global
            # item index. Segmented lanes scan a GATHERED subset of the
            # item axis and pass the original indices through item_ids so
            # the host merge can interleave per-lane logs back into the
            # sequential order.
            i=(
                jnp.asarray(item_ids, dtype=jnp.int32)
                if item_ids is not None
                else jnp.arange(I, dtype=jnp.int32)
            ),
            f_static=jnp.moveaxis(f_static, 1, 0),  # [I, J, T]
            openable=openable.T,  # [I, J]
        )
        # frozen mode keeps the (read-only) verdict tensor OUT of the scan
        # carry: one shared constant instead of one copy per vmapped lane
        aux0 = jnp.int32(0) if (prescreen and screen_frozen) else screen_init
        (state, log, ptr, _screen), _ = jax.lax.scan(
            step, (state, log0, jnp.int32(0), aux0), xs
        )
        return state, log, ptr

    return pack


def kernel_factories():
    """The kernel-factory registry, keyed by the compiled-program family
    each factory's output dispatches under (obs/proghealth FAMILIES plus
    the prescreen satellite) — the analysis/irlint catalog cross-checks
    its per-family contracts against this so a new factory without a
    contract fails loudly instead of shipping unchecked."""
    return {
        "prescreen": (make_prescreen_kernel,),
        "refresh": (make_screen_refresh_kernel,),
        "replan": (make_batched_replan_kernel, make_replan_verdict_kernel),
        "segment": (make_segment_partition_kernel,),
        "solve": (make_pack_kernel,),
    }
