"""Greedy packing kernel: lax.scan over FFD-ordered pods.

Replaces the serial Solve loop (reference scheduler.go:96-133,177-222) with a
device-resident scan over a fixed budget of node slots:

  slot state: accumulated requests, merged requirement masks, remaining
  instance-type mask, per-resource optimistic max-allocatable, pod count;
  global state: per-topology-group domain counts (ops/topology.py).

Per pod step:
  1. SCREEN all slots cheaply: taints ∧ requirement-compat ∧ optimistic fit
     (used + pod <= per-slot max over remaining types) ∧ topology viability.
  2. Rank candidates by the reference's order: existing nodes (index order)
     first, then open machines ascending pod count (scheduler.go:179-193).
  3. VERIFY the best candidate exactly: merge slot ∪ pod requirements,
     narrow by the topology domain choice (skew-rule argmin domain etc.),
     recompute the surviving instance types (compatible ∧ fits ∧ offering,
     machine.go:137-159). On failure, mask the candidate and retry (bounded
     while_loop).
  4. Otherwise OPEN a new slot from the first template whose fresh machine
     (fresh hostname domain) can host the pod (weight order,
     scheduler.go:195-221), honoring provisioner limits via pessimistic
     max-capacity subtraction (scheduler.go:276-293).
  5. COMMIT: update slot state and record the placement into topology domain
     counts (topology.go:120-143).

Slots [0, E) are pre-seeded with existing nodes (fixed capacity, no type
narrowing); machine slots open from E upward. Machine slot n's hostname
domain is the pre-registered dictionary value slot-hostname-n.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from karpenter_core_tpu.ops import compat
from karpenter_core_tpu.ops import topology as topo

BIG = jnp.float32(1e30)


class PackState(NamedTuple):
    used: jnp.ndarray  # [N, R]
    open: jnp.ndarray  # [N] bool
    is_existing: jnp.ndarray  # [N] bool
    tmpl: jnp.ndarray  # [N] int32 template id (machine slots)
    tol_idx: jnp.ndarray  # [N] int32 row into pod_tol_all
    pods: jnp.ndarray  # [N] int32
    allow: jnp.ndarray  # [N, V] bool (merged requirement masks)
    out: jnp.ndarray  # [N, K] bool
    defined: jnp.ndarray  # [N, K] bool
    tmask: jnp.ndarray  # [N, T] bool (remaining instance types; machine slots)
    cap: jnp.ndarray  # [N, R] optimistic capacity: existing=available,
    #                   machine=max over remaining types' allocatable
    nopen: jnp.ndarray  # scalar int32 — next free slot
    remaining: jnp.ndarray  # [J, R] provisioner remaining limit (+inf if none)
    tcounts: jnp.ndarray  # [G, V] topology domain counts (value-key groups)
    thost: jnp.ndarray  # [G, N] hostname-group counts per slot
    tdoms: jnp.ndarray  # [G, V] registered domains per group


def _segment_max_alloc(tmask: jnp.ndarray, type_alloc: jnp.ndarray) -> jnp.ndarray:
    """[..., T] bool, [T, R] -> [..., R] max allocatable over allowed types."""
    masked = jnp.where(tmask[..., None], type_alloc, -BIG)
    return masked.max(axis=-2)


def make_pack_kernel(
    segments,
    zone_seg,
    ct_seg,
    max_verify_tries: int = 16,
    topo_meta: Optional[topo.TopoMeta] = None,
):
    """Build the jittable packing fn for a fixed label geometry (+ topology
    group structure when the batch has topology constraints)."""

    zlo, zhi = zone_seg
    clo, chi = ct_seg
    has_topo = topo_meta is not None and len(topo_meta.groups) > 0

    def slot_compat_screen(state: PackState, prow):
        """[N] bool: pod-vs-slot requirement compatibility + custom rule
        (the node side is the slot's merged requirements)."""
        ok = jnp.ones(state.allow.shape[0], dtype=bool)
        slot_escape = compat.escape_flags(state.allow, state.out, state.defined, segments)
        for k, (lo, hi) in enumerate(segments):
            shared = state.defined[:, k] & prow["defined"][k]
            both_out = state.out[:, k] & prow["out"][k]
            if hi > lo:
                inter = (state.allow[:, lo:hi] & prow["allow"][lo:hi]).any(axis=-1)
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = slot_escape[:, k] & prow["escape"][k]
            ok &= (~shared) | nonempty | escapes
        # custom keys the pod defines (op not NotIn/DNE) must be defined on slot
        deny = prow["custom_deny"]  # [K]
        ok &= ~jnp.any(deny[None, :] & ~state.defined, axis=-1)
        return ok

    def merged_types_ok(m_allow, m_out, m_defined, new_used, base_tmask,
                        type_reqs, type_alloc, type_offering_ok):
        """[T]: surviving instance types for a merged requirement row
        (compatible ∧ fits ∧ hasOffering — machine.go:137-159)."""
        m_escape = compat.escape_flags(m_allow[None], m_out[None], m_defined[None], segments)[0]
        ok_t = jnp.ones(type_alloc.shape[0], dtype=bool)
        for k, (lo, hi) in enumerate(segments):
            shared = m_defined[k] & type_reqs["defined"][:, k]
            both_out = m_out[k] & type_reqs["out"][:, k]
            if hi > lo:
                inter = (m_allow[lo:hi][None, :] & type_reqs["allow"][:, lo:hi]).any(axis=-1)
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = m_escape[k] & type_reqs["escape"][:, k]
            ok_t &= (~shared) | nonempty | escapes
        fit_t = compat.fits(new_used[None, :], type_alloc)
        offer_t = (
            jnp.einsum(
                "tzc,z,c->t",
                type_offering_ok.astype(jnp.float32),
                m_allow[zlo:zhi].astype(jnp.float32),
                m_allow[clo:chi].astype(jnp.float32),
            )
            > 0.5
        )
        return base_tmask & ok_t & fit_t & offer_t

    def verify_slot(state: PackState, prow, n, type_reqs, type_alloc,
                    type_offering_ok, f_static_p):
        """Exact acceptance check on slot n.
        Returns (ok, new_tmask[T], narrow[V])."""
        slot_allow = state.allow[n]
        K = state.out.shape[1]
        if has_topo:
            t_viable, narrow, applied_keys = topo.topo_narrow_single(
                topo_meta, state.tcounts, state.thost, state.tdoms,
                prow["topo_own"], prow["topo_sel"], prow["allow"], slot_allow, n, K,
            )
        else:
            t_viable = jnp.bool_(True)
            narrow = jnp.ones_like(slot_allow)
            applied_keys = jnp.zeros(K, dtype=bool)

        m_allow = slot_allow & prow["allow"] & narrow
        # topology-narrowed keys become DEFINED concrete In-sets
        # (AddRequirements, topology.go:149-167)
        m_out = state.out[n] & prow["out"] & ~applied_keys
        m_defined = state.defined[n] | prow["defined"] | applied_keys
        new_used = state.used[n] + prow["requests"]

        new_tmask = merged_types_ok(
            m_allow, m_out, m_defined, new_used,
            state.tmask[n] & f_static_p[state.tmpl[n]],
            type_reqs, type_alloc, type_offering_ok,
        )
        is_existing = state.is_existing[n]
        fit_existing = compat.fits(new_used, state.cap[n])
        ok = t_viable & jnp.where(is_existing, fit_existing, new_tmask.any())
        return ok, new_tmask, narrow, applied_keys

    def record_topo(state: PackState, prow, m_allow, m_out, m_defined,
                    well_known, terms, slot_n):
        if not has_topo:
            return state
        nf_ok = topo.topo_node_filter_ok(
            topo_meta, terms, segments, well_known, m_allow, m_out, m_defined
        )
        tcounts, thost, tdoms = topo.topo_record(
            topo_meta, state.tcounts, state.thost, state.tdoms,
            prow["topo_own"], prow["topo_sel"], nf_ok, m_allow, m_out, slot_n,
        )
        return state._replace(tcounts=tcounts, thost=thost, tdoms=tdoms)

    def pack(
        state: PackState,
        pod_arrays: dict,
        f_static: jnp.ndarray,  # [J, P, T]
        openable: jnp.ndarray,  # [J, P]
        tmpl_reqs: dict,  # [J, ...]
        tmpl_daemon: jnp.ndarray,  # [J, R]
        tmpl_type_mask: jnp.ndarray,  # [J, T]
        type_reqs: dict,
        type_alloc: jnp.ndarray,
        type_capacity: jnp.ndarray,
        type_offering_ok: jnp.ndarray,
        well_known: jnp.ndarray = None,
        topo_terms: dict = None,
    ):
        N = state.used.shape[0]
        J = tmpl_daemon.shape[0]
        P = pod_arrays["requests"].shape[0]
        V = state.allow.shape[1]

        def step(state: PackState, i):
            prow = {
                "allow": pod_arrays["allow"][i],
                "out": pod_arrays["out"][i],
                "defined": pod_arrays["defined"][i],
                "escape": pod_arrays["escape"][i],
                "custom_deny": pod_arrays["custom_deny"][i],
                "requests": pod_arrays["requests"][i],
            }
            if has_topo:
                prow["topo_own"] = pod_arrays["topo_own"][i]
                prow["topo_sel"] = pod_arrays["topo_sel"][i]
            valid = pod_arrays["valid"][i]

            # -- screen --------------------------------------------------
            tol = pod_arrays["tol"][i][state.tol_idx]  # [N]
            fit_screen = compat.fits(state.used + prow["requests"][None, :], state.cap)
            req_screen = slot_compat_screen(state, prow)
            screen = state.open & tol & fit_screen & req_screen
            if has_topo:
                screen &= topo.topo_screen(
                    topo_meta, state.tcounts, state.thost, state.tdoms,
                    prow["topo_own"], prow["topo_sel"], prow["allow"], state.allow,
                )

            # rank: existing first by index, then machines by (pods, index)
            idx = jnp.arange(N, dtype=jnp.float32)
            score = jnp.where(
                state.is_existing,
                idx,
                jnp.float32(N) + state.pods.astype(jnp.float32) * N + idx,
            )
            score = jnp.where(screen, score, BIG)

            # -- verify loop ---------------------------------------------
            f_static_p = f_static[:, i, :]  # [J, T]

            def cond2(carry):
                found, tries, cand, score, _, _, _ = carry
                return (~found) & (tries < max_verify_tries) & (score.min() < BIG)

            def body(carry):
                found, tries, cand, score, tmask_out, narrow_out, keys_out = carry
                n = jnp.argmin(score)
                ok, new_tmask, narrow, applied_keys = verify_slot(
                    state, prow, n, type_reqs, type_alloc, type_offering_ok, f_static_p
                )
                score = score.at[n].set(BIG)
                return (
                    ok,
                    tries + 1,
                    jnp.where(ok, n, cand),
                    score,
                    jnp.where(ok, new_tmask, tmask_out),
                    jnp.where(ok, narrow, narrow_out),
                    jnp.where(ok, applied_keys, keys_out),
                )

            K = state.out.shape[1]
            found, _, cand, _, cand_tmask, cand_narrow, cand_keys = jax.lax.while_loop(
                cond2,
                body,
                (
                    jnp.bool_(False),
                    jnp.int32(0),
                    jnp.int32(-1),
                    score,
                    jnp.zeros_like(state.tmask[0]),
                    jnp.ones(V, dtype=bool),
                    jnp.zeros(K, dtype=bool),
                ),
            )

            # -- open new slot --------------------------------------------
            # fresh slot hostname is its slot identity (thost row = 0)
            cap_ok = jnp.all(
                type_capacity[None, :, :] <= state.remaining[:, None, :], axis=-1
            )  # [J, T]
            open_viable = []
            open_narrows = []
            open_outs = []
            open_defs = []
            open_types_rows = []
            for j in range(J):  # static unroll — J is the provisioner count
                fresh_allow = tmpl_reqs["allow"][j]
                if has_topo:
                    tv, tnarrow, tkeys = topo.topo_narrow_single(
                        topo_meta, state.tcounts, state.thost, state.tdoms,
                        prow["topo_own"], prow["topo_sel"], prow["allow"], fresh_allow,
                        state.nopen, K,
                    )
                else:
                    tv = jnp.bool_(True)
                    tnarrow = jnp.ones(V, dtype=bool)
                    tkeys = jnp.zeros(K, dtype=bool)
                m_allow_j = fresh_allow & prow["allow"] & tnarrow
                m_out_j = tmpl_reqs["out"][j] & prow["out"] & ~tkeys
                m_def_j = tmpl_reqs["defined"][j] | prow["defined"] | tkeys
                types_j = merged_types_ok(
                    m_allow_j, m_out_j, m_def_j,
                    tmpl_daemon[j] + prow["requests"],
                    tmpl_type_mask[j] & cap_ok[j] & f_static_p[j],
                    type_reqs, type_alloc, type_offering_ok,
                )
                open_viable.append(tv & types_j.any())
                open_narrows.append(m_allow_j)
                open_outs.append(m_out_j)
                open_defs.append(m_def_j)
                open_types_rows.append(types_j)
            can_open_j = jnp.stack(open_viable) & openable[:, i]  # [J]
            open_allow_rows = jnp.stack(open_narrows)  # [J, V]
            open_types = jnp.stack(open_types_rows)  # [J, T]
            j_choice = jnp.argmax(can_open_j)
            can_open = can_open_j.any() & (state.nopen < N)

            do_open = valid & (~found) & can_open
            do_assign = valid & (found | can_open)
            slot = jnp.where(found, cand, state.nopen)

            new_tmask = jnp.where(found, cand_tmask, open_types[j_choice])
            opened_allow = open_allow_rows[j_choice]
            opened_out = jnp.stack(open_outs)[j_choice]
            opened_defined = jnp.stack(open_defs)[j_choice]
            opened_used = tmpl_daemon[j_choice] + prow["requests"]
            opened_cap = _segment_max_alloc(new_tmask, type_alloc)

            def apply_found(state):
                n = cand
                m_allow = state.allow[n] & prow["allow"] & cand_narrow
                m_out = state.out[n] & prow["out"] & ~cand_keys
                m_defined = state.defined[n] | prow["defined"] | cand_keys
                new_used = state.used[n] + prow["requests"]
                is_existing = state.is_existing[n]
                new_cap = jnp.where(
                    is_existing, state.cap[n], _segment_max_alloc(cand_tmask, type_alloc)
                )
                state = state._replace(
                    used=state.used.at[n].set(new_used),
                    pods=state.pods.at[n].add(1),
                    allow=state.allow.at[n].set(m_allow),
                    out=state.out.at[n].set(m_out),
                    defined=state.defined.at[n].set(m_defined),
                    tmask=jnp.where(
                        is_existing, state.tmask, state.tmask.at[n].set(cand_tmask)
                    ),
                    cap=state.cap.at[n].set(new_cap),
                )
                return record_topo(
                    state, prow, m_allow, m_out, m_defined, well_known, topo_terms, n
                )

            def apply_open(state):
                n = state.nopen
                # pessimistic limit subtraction over surviving types
                # (scheduler.go:276-293)
                max_cap = jnp.where(new_tmask[:, None], type_capacity, -BIG).max(axis=0)
                max_cap = jnp.maximum(max_cap, 0.0)
                state = state._replace(
                    used=state.used.at[n].set(opened_used),
                    open=state.open.at[n].set(True),
                    is_existing=state.is_existing.at[n].set(False),
                    tmpl=state.tmpl.at[n].set(j_choice.astype(jnp.int32)),
                    tol_idx=state.tol_idx.at[n].set(j_choice.astype(jnp.int32)),
                    pods=state.pods.at[n].set(1),
                    allow=state.allow.at[n].set(opened_allow),
                    out=state.out.at[n].set(opened_out),
                    defined=state.defined.at[n].set(opened_defined),
                    tmask=state.tmask.at[n].set(new_tmask),
                    cap=state.cap.at[n].set(opened_cap),
                    nopen=state.nopen + 1,
                    remaining=state.remaining.at[j_choice].add(-max_cap),
                )
                return record_topo(
                    state, prow, opened_allow, opened_out, opened_defined,
                    well_known, topo_terms, n,
                )

            state = jax.lax.cond(
                valid & found,
                apply_found,
                lambda s: jax.lax.cond(do_open, apply_open, lambda x: x, s),
                state,
            )
            assigned = jnp.where(do_assign, slot, jnp.int32(-1))
            return state, assigned

        state, assigned = jax.lax.scan(step, state, jnp.arange(P, dtype=jnp.int32))
        return state, assigned

    return pack
