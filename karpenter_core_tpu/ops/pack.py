"""Greedy packing kernel: lax.scan over FFD-ordered pods.

Replaces the serial Solve loop (reference scheduler.go:96-133,177-222) with a
device-resident scan over a fixed budget of node slots:

  slot state: accumulated requests, merged requirement masks, remaining
  instance-type mask, per-resource optimistic max-allocatable, pod count.

Per pod step:
  1. SCREEN all slots cheaply: taints ∧ requirement-compat ∧ optimistic fit
     (used + pod <= per-slot max over remaining types).
  2. Rank candidates by the reference's order: existing nodes (index order)
     first, then open machines ascending pod count (scheduler.go:179-193).
  3. VERIFY the best candidate exactly: remaining types that are compatible
     with the MERGED slot∪pod requirements, fit the accumulated usage, and
     retain an available offering (machine.go:137-159). On failure, mask the
     candidate and retry (bounded while_loop).
  4. Otherwise OPEN a new slot from the first template whose fresh machine
     can host the pod (weight order, scheduler.go:195-221), honoring
     provisioner limits via pessimistic max-capacity subtraction
     (scheduler.go:276-293).

Slots [0, E) are pre-seeded with existing nodes (fixed capacity, no type
narrowing); machine slots open from E upward.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from karpenter_core_tpu.ops import compat
from karpenter_core_tpu.ops.feasibility import merge_reqsets

BIG = jnp.float32(1e30)


class PackState(NamedTuple):
    used: jnp.ndarray  # [N, R]
    open: jnp.ndarray  # [N] bool
    is_existing: jnp.ndarray  # [N] bool
    tmpl: jnp.ndarray  # [N] int32 template id (machine slots)
    tol_idx: jnp.ndarray  # [N] int32 row into pod_tol_all
    pods: jnp.ndarray  # [N] int32
    allow: jnp.ndarray  # [N, V] bool (merged requirement masks)
    out: jnp.ndarray  # [N, K] bool
    defined: jnp.ndarray  # [N, K] bool
    tmask: jnp.ndarray  # [N, T] bool (remaining instance types; machine slots)
    cap: jnp.ndarray  # [N, R] optimistic capacity: existing=available,
    #                   machine=max over remaining types' allocatable
    nopen: jnp.ndarray  # scalar int32 — next free slot
    remaining: jnp.ndarray  # [J, R] provisioner remaining limit (+inf if none)


def _segment_max_alloc(tmask: jnp.ndarray, type_alloc: jnp.ndarray) -> jnp.ndarray:
    """[..., T] bool, [T, R] -> [..., R] max allocatable over allowed types."""
    masked = jnp.where(tmask[..., None], type_alloc, -BIG)
    return masked.max(axis=-2)


def make_pack_kernel(segments, zone_seg, ct_seg, max_verify_tries: int = 16):
    """Build the jittable packing fn for a fixed label geometry."""

    zlo, zhi = zone_seg
    clo, chi = ct_seg

    def slot_compat_screen(state: PackState, prow):
        """[N] bool: pod-vs-slot requirement compatibility + custom rule
        (the node side is the slot's merged requirements)."""
        ok = jnp.ones(state.allow.shape[0], dtype=bool)
        slot_escape = compat.escape_flags(state.allow, state.out, state.defined, segments)
        for k, (lo, hi) in enumerate(segments):
            shared = state.defined[:, k] & prow["defined"][k]
            both_out = state.out[:, k] & prow["out"][k]
            if hi > lo:
                inter = (state.allow[:, lo:hi] & prow["allow"][lo:hi]).any(axis=-1)
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = slot_escape[:, k] & prow["escape"][k]
            ok &= (~shared) | nonempty | escapes
        # custom keys the pod defines (op not NotIn/DNE) must be defined on slot
        deny = prow["custom_deny"]  # [K]
        ok &= ~jnp.any(deny[None, :] & ~state.defined, axis=-1)
        return ok

    def verify_slot(state: PackState, prow, n, type_reqs, type_alloc, type_offering_ok, f_static_p):
        """Exact acceptance check on slot n; returns (ok, new_tmask[T])."""
        m_allow = state.allow[n] & prow["allow"]  # [V]
        m_out = state.out[n] & prow["out"]
        m_defined = state.defined[n] | prow["defined"]
        m_escape = compat.escape_flags(m_allow[None], m_out[None], m_defined[None], segments)[0]

        # per-type compat with merged requirements
        ok_t = jnp.ones(type_alloc.shape[0], dtype=bool)
        for k, (lo, hi) in enumerate(segments):
            shared = m_defined[k] & type_reqs["defined"][:, k]
            both_out = m_out[k] & type_reqs["out"][:, k]
            if hi > lo:
                inter = (m_allow[lo:hi][None, :] & type_reqs["allow"][:, lo:hi]).any(axis=-1)
                nonempty = both_out | inter
            else:
                nonempty = both_out
            escapes = m_escape[k] & type_reqs["escape"][:, k]
            ok_t &= (~shared) | nonempty | escapes

        new_used = state.used[n] + prow["requests"]  # [R]
        fit_t = compat.fits(new_used[None, :], type_alloc)  # [T]
        offer_t = (
            jnp.einsum(
                "tzc,z,c->t",
                type_offering_ok.astype(jnp.float32),
                m_allow[zlo:zhi].astype(jnp.float32),
                m_allow[clo:chi].astype(jnp.float32),
            )
            > 0.5
        )
        new_tmask = (
            state.tmask[n]
            & ok_t
            & fit_t
            & offer_t
            & f_static_p[state.tmpl[n]]
        )
        is_existing = state.is_existing[n]
        fit_existing = compat.fits(new_used, state.cap[n])
        ok = jnp.where(is_existing, fit_existing, new_tmask.any())
        return ok, new_tmask

    def commit(state: PackState, prow, n, new_tmask, type_alloc):
        m_allow = state.allow[n] & prow["allow"]
        m_out = state.out[n] & prow["out"]
        m_defined = state.defined[n] | prow["defined"]
        new_used = state.used[n] + prow["requests"]
        is_existing = state.is_existing[n]
        new_cap = jnp.where(
            is_existing, state.cap[n], _segment_max_alloc(new_tmask, type_alloc)
        )
        return state._replace(
            used=state.used.at[n].set(new_used),
            pods=state.pods.at[n].add(1),
            allow=state.allow.at[n].set(m_allow),
            out=state.out.at[n].set(m_out),
            defined=state.defined.at[n].set(m_defined),
            tmask=jnp.where(
                is_existing, state.tmask, state.tmask.at[n].set(new_tmask)
            ),
            cap=state.cap.at[n].set(new_cap),
        )

    def pack(
        state: PackState,
        pod_arrays: dict,  # allow [P,V], out/defined/escape/custom_deny [P,K],
        #                    requests [P,R], tol [P, J+E], valid [P]
        f_static: jnp.ndarray,  # [J, P, T]
        openable: jnp.ndarray,  # [J, P]
        tmpl_reqs: dict,  # [J, ...]
        tmpl_daemon: jnp.ndarray,  # [J, R]
        tmpl_type_mask: jnp.ndarray,  # [J, T]
        type_reqs: dict,
        type_alloc: jnp.ndarray,
        type_capacity: jnp.ndarray,
        type_offering_ok: jnp.ndarray,
    ):
        N = state.used.shape[0]
        J = tmpl_daemon.shape[0]
        P = pod_arrays["requests"].shape[0]

        def step(state: PackState, i):
            prow = {
                "allow": pod_arrays["allow"][i],
                "out": pod_arrays["out"][i],
                "defined": pod_arrays["defined"][i],
                "escape": pod_arrays["escape"][i],
                "custom_deny": pod_arrays["custom_deny"][i],
                "requests": pod_arrays["requests"][i],
            }
            valid = pod_arrays["valid"][i]

            # -- screen --------------------------------------------------
            tol = pod_arrays["tol"][i][state.tol_idx]  # [N]
            fit_screen = compat.fits(state.used + prow["requests"][None, :], state.cap)
            req_screen = slot_compat_screen(state, prow)
            screen = state.open & tol & fit_screen & req_screen

            # rank: existing first by index, then machines by (pods, index)
            idx = jnp.arange(N, dtype=jnp.float32)
            score = jnp.where(
                state.is_existing,
                idx,
                jnp.float32(N) + state.pods.astype(jnp.float32) * N + idx,
            )
            score = jnp.where(screen, score, BIG)

            # -- verify loop ---------------------------------------------
            def cond(carry):
                found, tries, cand, score, _ = carry
                return (~found) & (tries < max_verify_tries) & (score.min() < BIG)

            f_static_p = f_static[:, i, :]  # [J, T]

            def body(carry):
                found, tries, cand, score, tmask_out = carry
                n = jnp.argmin(score)
                ok, new_tmask = verify_slot(
                    state, prow, n, type_reqs, type_alloc, type_offering_ok, f_static_p
                )
                score = score.at[n].set(BIG)
                return (
                    ok,
                    tries + 1,
                    jnp.where(ok, n, cand),
                    score,
                    jnp.where(ok, new_tmask, tmask_out),
                )

            found, _, cand, _, cand_tmask = jax.lax.while_loop(
                cond,
                body,
                (
                    jnp.bool_(False),
                    jnp.int32(0),
                    jnp.int32(-1),
                    score,
                    jnp.zeros_like(state.tmask[0]),
                ),
            )

            # -- open new slot --------------------------------------------
            # first template (weight order) that can host the pod within limits
            cap_ok = jnp.all(
                type_capacity[None, :, :] <= state.remaining[:, None, :], axis=-1
            )  # [J, T]
            open_types = (
                f_static[:, i, :]
                & cap_ok
                & compat.fits(
                    (tmpl_daemon[:, None, :] + prow["requests"][None, None, :]),
                    type_alloc[None, :, :],
                )
            )  # [J, T]
            can_open_j = open_types.any(axis=-1) & openable[:, i]  # [J]
            j_choice = jnp.argmax(can_open_j)
            can_open = can_open_j.any() & (state.nopen < N)

            do_open = valid & (~found) & can_open
            do_assign = valid & (found | can_open)
            slot = jnp.where(found, cand, state.nopen)

            # build the opened slot's state row
            new_tmask = jnp.where(found, cand_tmask, open_types[j_choice])
            opened_allow = tmpl_reqs["allow"][j_choice] & prow["allow"]
            opened_out = tmpl_reqs["out"][j_choice] & prow["out"]
            opened_defined = tmpl_reqs["defined"][j_choice] | prow["defined"]
            opened_used = tmpl_daemon[j_choice] + prow["requests"]
            opened_cap = _segment_max_alloc(new_tmask, type_alloc)

            def apply_found(state):
                return commit(state, prow, cand, cand_tmask, type_alloc)

            def apply_open(state):
                n = state.nopen
                # pessimistic limit subtraction: max capacity over the opened
                # slot's surviving types (scheduler.go:276-293)
                max_cap = jnp.where(new_tmask[:, None], type_capacity, -BIG).max(axis=0)
                max_cap = jnp.maximum(max_cap, 0.0)
                return state._replace(
                    used=state.used.at[n].set(opened_used),
                    open=state.open.at[n].set(True),
                    is_existing=state.is_existing.at[n].set(False),
                    tmpl=state.tmpl.at[n].set(j_choice.astype(jnp.int32)),
                    tol_idx=state.tol_idx.at[n].set(j_choice.astype(jnp.int32)),
                    pods=state.pods.at[n].set(1),
                    allow=state.allow.at[n].set(opened_allow),
                    out=state.out.at[n].set(opened_out),
                    defined=state.defined.at[n].set(opened_defined),
                    tmask=state.tmask.at[n].set(new_tmask),
                    cap=state.cap.at[n].set(opened_cap),
                    nopen=state.nopen + 1,
                    remaining=state.remaining.at[j_choice].add(-max_cap),
                )

            state = jax.lax.cond(
                valid & found,
                apply_found,
                lambda s: jax.lax.cond(do_open, apply_open, lambda x: x, s),
                state,
            )
            assigned = jnp.where(do_assign, slot, jnp.int32(-1))
            return state, assigned

        state, assigned = jax.lax.scan(step, state, jnp.arange(P, dtype=jnp.int32))
        return state, assigned

    return pack
