"""Pallas TPU kernels for the hot solver ops.

The packing loop's dominant recurring op at 50k-pod scale is the slot
screen: Requirements.Compatible between every slot's merged requirement
row and one pod row (requirements.go:123-133 lowered to masks — see
ops/compat.rows_compat_m). The jnp form issues three separate [N, V] x
[V, K] matmuls (escape flags need allowed/excluded counts, compat needs
the intersection count) plus ~10 elementwise ops, each re-reading the
[N, V] allow matrix from HBM. The Pallas kernel tiles the slot axis and
makes ONE pass: the allow tile is read into VMEM once, all three MXU
contractions and the per-key boolean algebra run fused, and only the
final per-key verdict leaves the core.

Selection lives in compat.resolve_backend: 'mxu' (the jnp matmul form) by
default on accelerator backends — measured faster than this kernel at the
north-star geometry — with KCT_PALLAS=1 opting in; never on CPU, where the
unit tests run this same kernel in interpret mode instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _screen_kernel(pod_allow_ref, seg_ref, allow_ref, s_out_ref, s_def_ref,
                   p_out_ref, p_def_ref, p_esc_ref, deny_ref, verdict_ref):
    """One slot tile: fused escape-flag recovery + Compatible verdict.

    Inputs are 0/1 BF16 masks (exact for indicators; f32 staging doubled
    the HBM bytes and measurably lost to the plain matmul path at 50k
    scale): allow [TN, V]; s_out/s_def [TN, K]; pod rows [1, V]/[1, K];
    seg [V, K] key-membership. MXU contractions accumulate in f32, so the
    >0 tests stay exact. Output: per-key OK [TN, K] f32 (the caller ANDs
    over the real keys).
    """
    allow = allow_ref[:]
    seg = seg_ref[:]
    pod_allow = pod_allow_ref[:]

    one = jnp.bfloat16(1.0)
    # one pass over the allow tile: three MXU contractions (f32 accumulate)
    dot = lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    has_allow = dot(allow, seg)  # [TN, K] #allowed values per key
    has_excl = dot(one - allow, seg)  # [TN, K] #excluded values per key
    inter = dot(allow * pod_allow, seg)  # [TN, K] #shared values per key

    s_out = s_out_ref[:].astype(jnp.float32)
    s_def = s_def_ref[:].astype(jnp.float32)
    p_out = p_out_ref[:].astype(jnp.float32)
    p_def = p_def_ref[:].astype(jnp.float32)
    p_esc = p_esc_ref[:].astype(jnp.float32)
    deny = deny_ref[:].astype(jnp.float32)

    # escape = defined & ((out & has_excl) | (~out & ~has_allow))
    slot_escape = s_def * jnp.maximum(
        s_out * (has_excl > 0.5), (1.0 - s_out) * (has_allow < 0.5)
    )
    shared = s_def * p_def
    both_out = s_out * p_out
    nonempty = jnp.maximum(both_out, (inter > 0.5).astype(jnp.float32))
    escapes = slot_escape * p_esc
    # ~shared | nonempty | escapes, then the custom-deny rule
    key_ok = jnp.maximum(jnp.maximum(1.0 - shared, nonempty), escapes)
    key_ok = jnp.minimum(key_ok, 1.0 - deny * (1.0 - s_def))
    # batched grid adds a unit leading block dim on the output ref
    verdict_ref[...] = key_ok.reshape(verdict_ref.shape)


def slot_screen_pallas(slot_allow, slot_out, slot_defined, pod_row, seg_mat,
                       interpret: bool = False):
    """[N] Requirements.Compatible(slot rows, one pod row) as one fused
    Pallas pass. Semantics identical to compat.rows_compat_m (the jnp
    reference implementation the unit tests compare against)."""
    from jax.experimental import pallas as pl

    N, V = slot_allow.shape
    K = slot_out.shape[1]
    TN = 256
    Np = _round_up(max(N, TN), TN)
    Kp = _round_up(max(K, 128), 128)
    Vp = _round_up(max(V, 128), 128)

    def pad2(a, r, c):
        a = a.astype(jnp.bfloat16)
        return jnp.pad(a, ((0, r - a.shape[0]), (0, c - a.shape[1])))

    args = (
        pad2(pod_row["allow"][None, :], 1, Vp),
        pad2(jnp.asarray(seg_mat), Vp, Kp),
        pad2(slot_allow, Np, Vp),
        pad2(slot_out, Np, Kp),
        pad2(slot_defined, Np, Kp),
        pad2(pod_row["out"][None, :], 1, Kp),
        pad2(pod_row["defined"][None, :], 1, Kp),
        pad2(pod_row["escape"][None, :], 1, Kp),
        pad2(pod_row["custom_deny"][None, :], 1, Kp),
    )
    key_ok = pl.pallas_call(
        _screen_kernel,
        grid=(Np // TN,),
        in_specs=[
            pl.BlockSpec((1, Vp), lambda n: (0, 0)),
            pl.BlockSpec((Vp, Kp), lambda n: (0, 0)),
            pl.BlockSpec((TN, Vp), lambda n: (n, 0)),
            pl.BlockSpec((TN, Kp), lambda n: (n, 0)),
            pl.BlockSpec((TN, Kp), lambda n: (n, 0)),
            pl.BlockSpec((1, Kp), lambda n: (0, 0)),
            pl.BlockSpec((1, Kp), lambda n: (0, 0)),
            pl.BlockSpec((1, Kp), lambda n: (0, 0)),
            pl.BlockSpec((1, Kp), lambda n: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TN, Kp), lambda n: (n, 0)),
        out_shape=jax.ShapeDtypeStruct((Np, Kp), jnp.float32),
        interpret=interpret,
    )(*args)
    # padded keys: verdict 1.0 (shared=0 -> ~shared). AND over real keys.
    return jnp.all(key_ok[:N, :K] > 0.5, axis=-1)


def batched_slot_screen_pallas(slot_allow, slot_out, slot_defined, item_rows,
                               seg_mat, interpret: bool = False):
    """[B, N] Requirements.Compatible(slot rows, each of B item rows): the
    BATCHED form of slot_screen_pallas used by the pack kernel's prescreen
    (class×slot verdict precompute). Same fused kernel, grid extended over
    the item axis — each (item, slot-tile) cell reads its item row plus one
    allow tile and runs the three MXU contractions + key algebra in one
    pass. item_rows: dict with allow [B, V] / out, defined, escape,
    custom_deny [B, K]."""
    from jax.experimental import pallas as pl

    N, V = slot_allow.shape
    K = slot_out.shape[1]
    B = item_rows["allow"].shape[0]
    TN = 256
    Np = _round_up(max(N, TN), TN)
    Kp = _round_up(max(K, 128), 128)
    Vp = _round_up(max(V, 128), 128)

    def pad2(a, r, c):
        a = a.astype(jnp.bfloat16)
        return jnp.pad(a, ((0, r - a.shape[0]), (0, c - a.shape[1])))

    args = (
        pad2(item_rows["allow"], B, Vp),
        pad2(jnp.asarray(seg_mat), Vp, Kp),
        pad2(slot_allow, Np, Vp),
        pad2(slot_out, Np, Kp),
        pad2(slot_defined, Np, Kp),
        pad2(item_rows["out"], B, Kp),
        pad2(item_rows["defined"], B, Kp),
        pad2(item_rows["escape"], B, Kp),
        pad2(item_rows["custom_deny"], B, Kp),
    )
    key_ok = pl.pallas_call(
        _screen_kernel,
        grid=(B, Np // TN),
        in_specs=[
            pl.BlockSpec((1, Vp), lambda b, n: (b, 0)),
            pl.BlockSpec((Vp, Kp), lambda b, n: (0, 0)),
            pl.BlockSpec((TN, Vp), lambda b, n: (n, 0)),
            pl.BlockSpec((TN, Kp), lambda b, n: (n, 0)),
            pl.BlockSpec((TN, Kp), lambda b, n: (n, 0)),
            pl.BlockSpec((1, Kp), lambda b, n: (b, 0)),
            pl.BlockSpec((1, Kp), lambda b, n: (b, 0)),
            pl.BlockSpec((1, Kp), lambda b, n: (b, 0)),
            pl.BlockSpec((1, Kp), lambda b, n: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, TN, Kp), lambda b, n: (b, n, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Np, Kp), jnp.float32),
        interpret=interpret,
    )(*args)
    return jnp.all(key_ok[:, :N, :K] > 0.5, axis=-1)
