"""Static pod×instance-type feasibility kernel.

Computes, for each machine template j, F_static[j, P, T] =
    Compatible(template_j, pod)                   (machine.go:77)
  ∧ Intersects(template_j ∩ pod, instance_type)   (machine.go:137-145)
  ∧ hasOffering(type, zones/cts of template∩pod)  (machine.go:152-159)
  ∧ pod tolerates template taints                 (machine.go:63-65)
  ∧ template offers the type
plus openable[j, P] = F_static ∧ fits(daemon_j + pod) — "a fresh machine from
template j could host this pod alone".

Resource fits against ACCUMULATED machine usage is intentionally excluded from
F_static: the packing kernel (ops/pack.py) applies it per step.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from karpenter_core_tpu.ops import compat


def reqset_row(rs, i):
    return {k: v[i] for k, v in rs.items()}


def merge_reqsets(a, b):
    """Intersection of two requirement rows/batches (broadcastable):
    Requirements.Add semantics on masks (requirement.go:117-150)."""
    return {
        "allow": a["allow"] & b["allow"],
        "out": a["out"] & b["out"],
        "defined": a["defined"] | b["defined"],
    }


def feasibility_static(
    pod_reqs: dict,  # allow [P,V], out/defined/escape [P,K]
    tmpl_reqs: dict,  # [J, ...]
    type_reqs: dict,  # [T, ...]
    pod_tol: jnp.ndarray,  # [P, J]
    tmpl_type_mask: jnp.ndarray,  # [J, T]
    type_offering_ok: jnp.ndarray,  # [T, Z, C]
    zone_seg: Tuple[int, int],
    ct_seg: Tuple[int, int],
    segments,
    well_known: jnp.ndarray,
) -> jnp.ndarray:
    """Returns F_static [J, P, T] bool."""
    J = tmpl_reqs["allow"].shape[0]
    outs = []
    for j in range(J):  # J is small (provisioner count); static unroll
        tmpl = {k: v[j : j + 1] for k, v in tmpl_reqs.items()}
        # Compatible(template, pod): [1, P] -> [P]
        comp_tp = compat.pairwise_compatible(tmpl, pod_reqs, segments, well_known)[0]

        # merged machine requirements M = template ∩ pod
        merged = merge_reqsets(
            {k: tmpl_reqs[k][j][None, :] for k in ("allow", "out", "defined")},
            {k: pod_reqs[k] for k in ("allow", "out", "defined")},
        )  # [P, ...]
        merged["escape"] = compat.escape_flags(
            merged["allow"], merged["out"], merged["defined"], segments
        )

        # Intersects(M, type): [P, T]
        inter_ok = compat.pairwise_intersects(merged, type_reqs, segments)

        # hasOffering: any available offering in M's zone/ct masks [P, T]
        zlo, zhi = zone_seg
        clo, chi = ct_seg
        zone_allow = merged["allow"][:, zlo:zhi]  # [P, Z]
        ct_allow = merged["allow"][:, clo:chi]  # [P, C]
        offer_ok = (
            jnp.einsum(
                "tzc,pz,pc->pt",
                type_offering_ok.astype(jnp.float32),
                zone_allow.astype(jnp.float32),
                ct_allow.astype(jnp.float32),
            )
            > 0.5
        )

        f = (
            comp_tp[:, None]
            & pod_tol[:, j][:, None]
            & tmpl_type_mask[j][None, :]
            & inter_ok
            & offer_ok
        )
        outs.append(f)
    return jnp.stack(outs, axis=0)


def openable_mask(
    f_static: jnp.ndarray,  # [J, P, T]
    pod_requests: jnp.ndarray,  # [P, R]
    tmpl_daemon: jnp.ndarray,  # [J, R]
    type_alloc: jnp.ndarray,  # [T, R]
) -> jnp.ndarray:
    """[J, P]: a fresh machine from template j can host the pod alone."""
    # [J, P, T]: daemon_j + pod_p fits type_t
    req = tmpl_daemon[:, None, :] + pod_requests[None, :, :]  # [J, P, R]
    fit = compat.fits(req[:, :, None, :], type_alloc[None, None, :, :])  # [J, P, T]
    return (f_static & fit).any(axis=-1)
