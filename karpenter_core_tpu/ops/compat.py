"""Device primitives for the requirement algebra.

Lowers the set-algebra of pkg/scheduling/{requirement,requirements}.go onto
dense masks (see solver/encode.py for the encoding):

  nonempty(A ∩ B) per key  =  (outA & outB) | any_v(allowA & allowB)
  Intersects(a, b) fails on a shared-defined key with empty intersection
    unless BOTH operators are NotIn/DoesNotExist (requirements.go:189-206)
  Compatible(node, pod) additionally denies custom (non-well-known) keys the
    node side doesn't define, unless pod op is NotIn/DoesNotExist
    (requirements.go:123-133)

All per-key reductions are static Python loops over dictionary segments at
trace time, so XLA sees fixed-shape slices and fuses the whole thing.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

Segments = List[Tuple[int, int]]  # per key: (lo, hi) into the flat value axis


def resolve_backend(device=None) -> str:
    """Pick the kernel lowering for the device the program will RUN on:
    'sliced' (per-key loop, CPU), 'mxu' (matmul-fused), or 'pallas' (fused
    single-pass screen). Kernel builders take this as an explicit option so
    tracing for a non-default device can't bake the wrong branch
    (jax.default_backend() is only the fallback when no device is given)."""
    from karpenter_core_tpu.obs import envflags

    platform = device.platform if device is not None else jax.default_backend()
    if platform == "cpu":
        return "sliced"
    # Default to the plain matmul form: measured on v5e at the north-star
    # geometry (12.5k slots x 2k values, 1k items) it beats the fused
    # Pallas screen (575ms vs 638ms device solve) — the screen's padded
    # staging outweighs its fusion win at this scale. KCT_PALLAS=1 opts in.
    if envflags.raw("KCT_PALLAS", "auto") in ("1", "true", "on"):
        return "pallas"
    return "mxu"


def resolve_screen_mode() -> str:
    """Pick the pack kernel's slot-screen strategy.

    'prescreen' (default): the per-(item-class, slot) requirement screen is
    hoisted out of the scan — one batched [I, N] verdict tensor computed
    before the scan, refreshed incrementally for only the slot rows a
    commit writes (ops/pack.py). 'tiered': the original per-step full
    screen, kept as the fallback path. KCT_PACK_SCREEN ∈ {auto, prescreen,
    tiered}; selection happens at trace time, so flipping the flag mints a
    new compiled program (solver caches key on the resolved mode)."""
    from karpenter_core_tpu.obs import envflags

    mode = envflags.raw("KCT_PACK_SCREEN", "auto").strip().lower()
    if mode in ("tiered", "prescreen"):
        return mode
    return "prescreen"


def resolve_pack_scan() -> str:
    """Pick the pack kernel's SCAN strategy (ISSUE 14).

    'sequential': the proven single lax.scan over all FFD-ordered items.
    'segmented': partition items into conflict-independent segments via the
    resident [N, C] verdict tensor (ops/pack.make_segment_partition_kernel),
    scan segments in parallel (vmapped lanes against disjoint slot
    partitions), and merge on the host — byte-identical to sequential by
    construction, degrading to the sequential kernel whenever the
    disjointness proof fails (topology/ports/volumes/finite limits, a
    single conflict component, or post-hoc slot-budget overflow).
    'auto' currently resolves to 'sequential': the segmented win is only
    proven on CPU fallback so far (docs/solver-perf.md "segmented
    packing"); flip after a real-TPU round (ROADMAP item 1) lands the
    numbers. KCT_PACK_SCAN ∈ {auto, sequential, segmented}. This is a
    DISPATCH policy like the incremental mode — the sequential program's
    compiled key never changes; segmented dispatches extra programs under
    their own scan-mode-suffixed keys."""
    from karpenter_core_tpu.obs import envflags

    mode = envflags.raw("KCT_PACK_SCAN", "auto").strip().lower()
    if mode in ("sequential", "segmented"):
        return mode
    return "sequential"


def resolve_incremental_mode() -> str:
    """Pick the incremental (delta re-solve) screen policy.

    'on' (the 'auto' default): under the prescreen screen mode, consecutive
    solves at one geometry keep the verdict tensor resident and replay only
    the changed existing-slot rows / verdict columns through the delta
    refresh program (solver/incremental.py); the full precompute stays the
    fallback for wide deltas, geometry changes, and state-diff-feed faults.
    'off': always run the full precompute. KCT_INCREMENTAL ∈ {auto, on,
    off}. Unlike the screen mode this is a DISPATCH policy, not a trace
    branch — both paths produce bit-identical tensors, so no compiled
    program keys on it."""
    from karpenter_core_tpu.obs import envflags

    mode = envflags.raw("KCT_INCREMENTAL", "auto").strip().lower()
    if mode in ("on", "off"):
        return mode
    return "on"


def seg_matrix(segments: Segments, V: int):
    """Static [V, K] one-hot membership matrix: column k marks the values of
    key k. Turns every per-key any-reduction into ONE bf16 matmul on the MXU
    (f32 accumulate keeps the >0 test exact), replacing K sliced reductions —
    the op-count killer inside the packing scan."""
    import numpy as np

    K = len(segments)
    m = np.zeros((V, K), dtype=np.float32)
    for k, (lo, hi) in enumerate(segments):
        m[lo:hi, k] = 1.0
    return m


def segment_any_m(mask: jnp.ndarray, seg_mat) -> jnp.ndarray:
    """[..., V] bool -> [..., K] bool via one matmul (MXU path)."""
    counts = jax.lax.dot_general(
        mask.astype(jnp.bfloat16),
        jnp.asarray(seg_mat, dtype=jnp.bfloat16),
        (((mask.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return counts > 0.5


def escape_flags_m(allow, out, defined, seg_mat) -> jnp.ndarray:
    """escape_flags with matmul-fused segment reductions (2 matmuls total)."""
    has_allow = segment_any_m(allow, seg_mat)
    has_excl = segment_any_m(~allow, seg_mat)
    return defined & ((out & has_excl) | (~out & ~has_allow))


def rows_compat_m(node, pod_row, seg_mat, custom_deny=None):
    """Batched Requirements.Compatible(node_rows, one pod row) -> [N] bool.

    node: dict with allow [N,V] / out,defined [N,K] (escape derived here);
    pod_row: dict with allow [V] / out,defined,escape [K] (+ custom_deny [K]).
    Fuses the per-key loop of pairwise_compatible into 3 matmuls."""
    node_escape = escape_flags_m(node["allow"], node["out"], node["defined"], seg_mat)
    shared = node["defined"] & pod_row["defined"][None, :]
    both_out = node["out"] & pod_row["out"][None, :]
    inter = segment_any_m(node["allow"] & pod_row["allow"][None, :], seg_mat)
    escapes = node_escape & pod_row["escape"][None, :]
    ok = ((~shared) | both_out | inter | escapes).all(axis=-1)
    if custom_deny is not None:
        ok &= ~jnp.any(custom_deny[None, :] & ~node["defined"], axis=-1)
    return ok


def row_vs_rows_compat_m(m_allow, m_out, m_defined, m_escape, rows, seg_mat):
    """Intersects(one merged row, batch rows) -> [T] bool, matmul-fused.
    rows: dict with allow [T,V] / out,defined,escape [T,K]."""
    shared = m_defined[None, :] & rows["defined"]
    both_out = m_out[None, :] & rows["out"]
    inter = segment_any_m(rows["allow"] & m_allow[None, :], seg_mat)
    escapes = m_escape[None, :] & rows["escape"]
    return ((~shared) | both_out | inter | escapes).all(axis=-1)


def segment_any(mask: jnp.ndarray, segments: Segments) -> jnp.ndarray:
    """[..., V] bool -> [..., K] bool: any within each key's segment."""
    cols = [
        mask[..., lo:hi].any(axis=-1)
        if hi > lo
        else jnp.zeros(mask.shape[:-1], dtype=bool)
        for lo, hi in segments
    ]
    return jnp.stack(cols, axis=-1)


def escape_flags(
    allow: jnp.ndarray, out: jnp.ndarray, defined: jnp.ndarray, segments: Segments
) -> jnp.ndarray:
    """Recover operator ∈ {NotIn, DoesNotExist} for (possibly merged)
    requirement rows (requirement.go:186-197):
      NotIn          = complement & excluded-values nonempty
      DoesNotExist   = ~complement & allowed empty
    """
    has_allow = segment_any(allow, segments)
    has_excl = segment_any(~allow, segments)
    return defined & ((out & has_excl) | (~out & ~has_allow))


def pairwise_nonempty_key(
    allow_a: jnp.ndarray,  # [A, V]
    out_a: jnp.ndarray,  # [A, K]
    allow_b: jnp.ndarray,  # [B, V]
    out_b: jnp.ndarray,  # [B, K]
    k: int,
    lo: int,
    hi: int,
) -> jnp.ndarray:
    """[A, B] nonempty(A_i ∩ B_j) for key k via one MXU matmul."""
    both_out = out_a[:, k : k + 1] & out_b[:, k].T  # [A, B]
    if hi == lo:
        return both_out
    inter = (
        jnp.matmul(
            allow_a[:, lo:hi].astype(jnp.bfloat16),
            allow_b[:, lo:hi].astype(jnp.bfloat16).T,
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )
    return both_out | inter


def pairwise_intersects(a, b, segments: Segments) -> jnp.ndarray:
    """[A, B] Requirements.Intersects between rows of two ReqSet pytrees
    (dicts with allow/out/defined/escape)."""
    ok = None
    for k, (lo, hi) in enumerate(segments):
        shared = a["defined"][:, k : k + 1] & b["defined"][None, :, k]
        nonempty = pairwise_nonempty_key(a["allow"], a["out"], b["allow"], b["out"], k, lo, hi)
        escapes = a["escape"][:, k : k + 1] & b["escape"][None, :, k]
        key_ok = (~shared) | nonempty | escapes
        ok = key_ok if ok is None else (ok & key_ok)
    if ok is None:
        ok = jnp.ones((a["allow"].shape[0], b["allow"].shape[0]), dtype=bool)
    return ok


def pairwise_compatible(node, pod, segments: Segments, well_known: jnp.ndarray) -> jnp.ndarray:
    """[Nnode, Npod] Requirements.Compatible(node_side, pod_side):
    Intersects plus the custom-label-must-be-defined rule."""
    ok = pairwise_intersects(node, pod, segments)
    # custom keys: pod defines, node doesn't, op not NotIn/DNE -> incompatible
    custom = ~well_known  # [K]
    deny = (
        custom[None, :]
        & pod["defined"]
        & ~pod["escape"]
    )  # [Npod, K]
    # [Nnode, Npod]: any denied key the node does not define
    denied = jnp.any(deny[None, :, :] & ~node["defined"][:, None, :], axis=-1)
    return ok & ~denied


def rows_nonempty(allow_a, out_a, allow_b, out_b, segments: Segments) -> jnp.ndarray:
    """Row-aligned nonempty: a and b both [..., V]/[..., K] broadcastable;
    returns [..., K]."""
    cols = []
    for k, (lo, hi) in enumerate(segments):
        both_out = out_a[..., k] & out_b[..., k]
        if hi > lo:
            inter = (allow_a[..., lo:hi] & allow_b[..., lo:hi]).any(axis=-1)
            cols.append(both_out | inter)
        else:
            cols.append(both_out)
    return jnp.stack(cols, axis=-1)


def fits(requests: jnp.ndarray, alloc: jnp.ndarray) -> jnp.ndarray:
    """resources.Fits on device: requests [..., R] vs alloc [..., R] ->
    [...] bool. Any negative allocatable entry never fits."""
    return jnp.all((requests <= alloc) & (alloc >= 0.0), axis=-1)
