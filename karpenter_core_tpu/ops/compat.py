"""Device primitives for the requirement algebra.

Lowers the set-algebra of pkg/scheduling/{requirement,requirements}.go onto
dense masks (see solver/encode.py for the encoding):

  nonempty(A ∩ B) per key  =  (outA & outB) | any_v(allowA & allowB)
  Intersects(a, b) fails on a shared-defined key with empty intersection
    unless BOTH operators are NotIn/DoesNotExist (requirements.go:189-206)
  Compatible(node, pod) additionally denies custom (non-well-known) keys the
    node side doesn't define, unless pod op is NotIn/DoesNotExist
    (requirements.go:123-133)

All per-key reductions are static Python loops over dictionary segments at
trace time, so XLA sees fixed-shape slices and fuses the whole thing.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

Segments = List[Tuple[int, int]]  # per key: (lo, hi) into the flat value axis


def segment_any(mask: jnp.ndarray, segments: Segments) -> jnp.ndarray:
    """[..., V] bool -> [..., K] bool: any within each key's segment."""
    cols = [
        mask[..., lo:hi].any(axis=-1)
        if hi > lo
        else jnp.zeros(mask.shape[:-1], dtype=bool)
        for lo, hi in segments
    ]
    return jnp.stack(cols, axis=-1)


def escape_flags(
    allow: jnp.ndarray, out: jnp.ndarray, defined: jnp.ndarray, segments: Segments
) -> jnp.ndarray:
    """Recover operator ∈ {NotIn, DoesNotExist} for (possibly merged)
    requirement rows (requirement.go:186-197):
      NotIn          = complement & excluded-values nonempty
      DoesNotExist   = ~complement & allowed empty
    """
    has_allow = segment_any(allow, segments)
    has_excl = segment_any(~allow, segments)
    return defined & ((out & has_excl) | (~out & ~has_allow))


def pairwise_nonempty_key(
    allow_a: jnp.ndarray,  # [A, V]
    out_a: jnp.ndarray,  # [A, K]
    allow_b: jnp.ndarray,  # [B, V]
    out_b: jnp.ndarray,  # [B, K]
    k: int,
    lo: int,
    hi: int,
) -> jnp.ndarray:
    """[A, B] nonempty(A_i ∩ B_j) for key k via one MXU matmul."""
    both_out = out_a[:, k : k + 1] & out_b[:, k].T  # [A, B]
    if hi == lo:
        return both_out
    inter = (
        jnp.matmul(
            allow_a[:, lo:hi].astype(jnp.bfloat16),
            allow_b[:, lo:hi].astype(jnp.bfloat16).T,
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )
    return both_out | inter


def pairwise_intersects(a, b, segments: Segments) -> jnp.ndarray:
    """[A, B] Requirements.Intersects between rows of two ReqSet pytrees
    (dicts with allow/out/defined/escape)."""
    ok = None
    for k, (lo, hi) in enumerate(segments):
        shared = a["defined"][:, k : k + 1] & b["defined"][None, :, k]
        nonempty = pairwise_nonempty_key(a["allow"], a["out"], b["allow"], b["out"], k, lo, hi)
        escapes = a["escape"][:, k : k + 1] & b["escape"][None, :, k]
        key_ok = (~shared) | nonempty | escapes
        ok = key_ok if ok is None else (ok & key_ok)
    if ok is None:
        ok = jnp.ones((a["allow"].shape[0], b["allow"].shape[0]), dtype=bool)
    return ok


def pairwise_compatible(node, pod, segments: Segments, well_known: jnp.ndarray) -> jnp.ndarray:
    """[Nnode, Npod] Requirements.Compatible(node_side, pod_side):
    Intersects plus the custom-label-must-be-defined rule."""
    ok = pairwise_intersects(node, pod, segments)
    # custom keys: pod defines, node doesn't, op not NotIn/DNE -> incompatible
    custom = ~well_known  # [K]
    deny = (
        custom[None, :]
        & pod["defined"]
        & ~pod["escape"]
    )  # [Npod, K]
    # [Nnode, Npod]: any denied key the node does not define
    denied = jnp.any(deny[None, :, :] & ~node["defined"][:, None, :], axis=-1)
    return ok & ~denied


def rows_nonempty(allow_a, out_a, allow_b, out_b, segments: Segments) -> jnp.ndarray:
    """Row-aligned nonempty: a and b both [..., V]/[..., K] broadcastable;
    returns [..., K]."""
    cols = []
    for k, (lo, hi) in enumerate(segments):
        both_out = out_a[..., k] & out_b[..., k]
        if hi > lo:
            inter = (allow_a[..., lo:hi] & allow_b[..., lo:hi]).any(axis=-1)
            cols.append(both_out | inter)
        else:
            cols.append(both_out)
    return jnp.stack(cols, axis=-1)


def fits(requests: jnp.ndarray, alloc: jnp.ndarray) -> jnp.ndarray:
    """resources.Fits on device: requests [..., R] vs alloc [..., R] ->
    [...] bool. Any negative allocatable entry never fits."""
    return jnp.all((requests <= alloc) & (alloc >= 0.0), axis=-1)
