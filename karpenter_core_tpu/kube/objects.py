"""k8s-lite object model.

The reference consumes k8s.io/api + apimachinery types; this framework has no
real cluster dependency, so we carry a minimal-but-faithful dataclass model of
the objects the scheduling/controller stack actually touches: Pod, Node, PVC,
PV, StorageClass, CSINode, PDB, plus the selector/affinity/taint sub-types.

Resource quantities are plain floats in a `dict[str, float]` ResourceList
(cpu in cores, memory/ephemeral-storage in bytes, counts for pods/extended
resources) — parsed from k8s quantity strings by utils.resources.parse_quantity.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ResourceList = Dict[str, float]

_uid_counter = itertools.count(1)


def _new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


# ---------------------------------------------------------------------------
# metadata


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=_new_uid)
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    resource_version: int = 0


class NamespacedName(tuple):
    """Hashable (namespace, name) object key."""

    def __new__(cls, namespace: str, name: str):
        return super().__new__(cls, (namespace, name))

    @property
    def namespace(self) -> str:
        return self[0]

    @property
    def name(self) -> str:
        return self[1]

    def __str__(self) -> str:
        return f"{self[0]}/{self[1]}"


def object_key(obj) -> NamespacedName:
    return NamespacedName(obj.metadata.namespace, obj.metadata.name)


# ---------------------------------------------------------------------------
# selectors / affinity (semantics of k8s.io/api/core/v1 types)


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


_AFFINITY_WIRE = {
    "required": "requiredDuringSchedulingIgnoredDuringExecution",
    "preferred": "preferredDuringSchedulingIgnoredDuringExecution",
}


@dataclass
class NodeAffinity:
    # required terms are ORed (any one term may match); expressions within a
    # term are ANDed — mirrors v1.NodeSelector semantics.
    required: List[NodeSelectorTerm] = field(default_factory=list)
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)

    # the k8s wire names (kube/serialization.py consults these; a real
    # apiserver payload would otherwise never populate `required`) — and
    # NodeAffinity's required list additionally wraps in a NodeSelector
    # object on the wire
    _WIRE_OVERRIDES = _AFFINITY_WIRE
    _WIRE_WRAP = {"required": "nodeSelectorTerms"}


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            val = labels.get(expr.key)
            if expr.operator == "In":
                if val is None or val not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if val is not None and val in expr.values:
                    return False
            elif expr.operator == "Exists":
                if expr.key not in labels:
                    return False
            elif expr.operator == "DoesNotExist":
                if expr.key in labels:
                    return False
            else:
                return False
        return True


@dataclass
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm = None


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)

    _WIRE_OVERRIDES = _AFFINITY_WIRE


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)

    _WIRE_OVERRIDES = _AFFINITY_WIRE


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None


# ---------------------------------------------------------------------------
# taints / tolerations (semantics of v1.Taint / v1.Toleration)

TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_EFFECT_NO_SCHEDULE

    def match_taint(self, other: "Taint") -> bool:
        # v1.Taint.MatchTaint: key and effect equality (value ignored)
        return self.key == other.key and self.effect == other.effect


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates_taint(self, taint: Taint) -> bool:
        """v1.Toleration.ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        if self.operator == "Exists":
            # k8s requires an empty value with Exists
            return self.value == ""
        return False


# ---------------------------------------------------------------------------
# pods


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    host_ip: str = ""
    protocol: str = "TCP"


@dataclass
class ResourceRequirements:
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)


@dataclass
class Container:
    name: str = "container"
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""


@dataclass
class EphemeralVolumeSource:
    """Generic ephemeral volume: carries the claim-template storage class
    (v1.EphemeralVolumeSource, validated in volumetopology.go:162-170)."""

    storage_class_name: Optional[str] = None


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None
    ephemeral: Optional[EphemeralVolumeSource] = None


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"
    termination_grace_period_seconds: Optional[int] = None


@dataclass
class Condition:
    """Shared condition shape for Pod/Node/Machine/Provisioner status."""

    type: str = ""
    status: str = ""  # True | False | Unknown
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


PodCondition = Condition


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: List[PodCondition] = field(default_factory=list)
    start_time: Optional[float] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def key(self) -> NamespacedName:
        return object_key(self)


# ---------------------------------------------------------------------------
# nodes


@dataclass
class NodeSpec:
    provider_id: str = ""
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False


NodeCondition = Condition


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # nodes are cluster-scoped

    @property
    def name(self) -> str:
        return self.metadata.name

    def ready(self) -> bool:
        for c in self.status.conditions:
            if c.type == "Ready":
                return c.status == "True"
        return False


# ---------------------------------------------------------------------------
# storage


@dataclass
class PersistentVolumeClaimSpec:
    storage_class_name: Optional[str] = None
    volume_name: str = ""


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)


@dataclass
class CSIPersistentVolumeSource:
    driver: str = ""


@dataclass
class PersistentVolumeSpec:
    csi: Optional[CSIPersistentVolumeSource] = None
    node_affinity_required: List[NodeSelectorTerm] = field(default_factory=list)
    storage_class_name: str = ""


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)

    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class TopologySelectorLabelRequirement:
    key: str = ""
    values: List[str] = field(default_factory=list)


@dataclass
class TopologySelectorTerm:
    match_label_expressions: List[TopologySelectorLabelRequirement] = field(default_factory=list)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    allowed_topologies: List[TopologySelectorTerm] = field(default_factory=list)

    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class CSINodeDriver:
    name: str = ""
    allocatable_count: Optional[int] = None


@dataclass
class CSINode:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: List[CSINodeDriver] = field(default_factory=list)

    def __post_init__(self):
        self.metadata.namespace = ""


# ---------------------------------------------------------------------------
# policy


@dataclass
class PodDisruptionBudgetSpec:
    selector: Optional[LabelSelector] = None
    min_available: Optional[object] = None  # int or percent string "50%"
    max_unavailable: Optional[object] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Secret:
    """Opaque secret (base64-encoded values in `data`) — carries the webhook
    serving cert (chart secret-webhook-cert.yaml)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"


@dataclass
class DaemonSet:
    """Minimal DaemonSet: carries the pod template the scheduler uses to
    compute per-template daemon overhead."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_template_spec: Optional["PodSpec"] = None


@dataclass
class ObjectReference:
    """core/v1 ObjectReference (the involvedObject of an Event)."""

    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class Event:
    """core/v1 Event — the cluster-visible record the Recorder posts so
    `kubectl describe` shows scheduling decisions (reference: client-go
    record.EventRecorder via pkg/events/recorder.go:50-56)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    first_timestamp: Optional[float] = None
    last_timestamp: Optional[float] = None
    reporting_component: str = "karpenter"


@dataclass
class LeaseSpec:
    """coordination.k8s.io/v1 LeaseSpec (leader-election record,
    reference operator.go:108-110 LeaderElectionResourceLock "leases")."""

    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: Optional[float] = None
    renew_time: Optional[float] = None
    lease_transitions: int = 0


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)


# Well-known label/condition constants (k8s.io/api/core/v1 well_known_labels.go)
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE_STABLE = "node.kubernetes.io/instance-type"
LABEL_ARCH_STABLE = "kubernetes.io/arch"
LABEL_OS_STABLE = "kubernetes.io/os"
LABEL_FAILURE_DOMAIN_BETA_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_FAILURE_DOMAIN_BETA_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_INSTANCE_TYPE_BETA = "beta.kubernetes.io/instance-type"

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"

TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"
