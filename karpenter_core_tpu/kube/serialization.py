"""k8s JSON <-> object-model conversion.

The object model (kube/objects.py, api/*.py) uses snake_case dataclasses
with float quantities; the wire format (AdmissionReview payloads, a real
apiserver) uses camelCase JSON with string quantities. `from_k8s_dict` /
`to_k8s_dict` convert generically from the dataclass type hints, so every
registered kind round-trips without per-type marshalling code — the analog
of the reference's generated deepcopy/JSON tags (zz_generated.deepcopy.go).
"""
from __future__ import annotations

import dataclasses
import typing

from karpenter_core_tpu.utils.resources import parse_quantity


def camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


# fields whose wire names aren't a plain camelCase of the attribute.
# provider_id rides as "providerID" (capital ID — k8s convention, and the
# shipped Machine CRD schema's own spelling): a real apiserver would
# silently drop "providerId" writes and the adapter would decode '' back,
# breaking node<->machine matching (caught by tests/test_wire_fixtures.py).
_SPECIAL_WIRE = {
    "creation_timestamp": "creationTimestamp",
    "deletion_timestamp": "deletionTimestamp",
    "resource_version": "resourceVersion",
    "provider_id": "providerID",
}


def _wire_name(cls, fname: str) -> str:
    overrides = getattr(cls, "_WIRE_OVERRIDES", None)
    if overrides and fname in overrides:
        return overrides[fname]
    return _SPECIAL_WIRE.get(fname, camel(fname))


def _is_time_field(name: str) -> bool:
    """Float fields holding epoch seconds that ride the wire as RFC3339
    (k8s Time/MicroTime): creationTimestamp, deletionTimestamp, the Event
    first/lastTimestamp, the Lease acquire/renewTime. A real apiserver
    always stamps these — the adapter must parse them, not feed them to
    the quantity parser."""
    return name.endswith("_timestamp") or name.endswith("_time")


def _parse_time(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    from datetime import datetime

    return datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()


def _format_time(v: float) -> str:
    from datetime import datetime, timezone

    return (
        datetime.fromtimestamp(float(v), timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )


def _strip_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _is_quantity_map(tp) -> bool:
    """Dict[str, float] fields are ResourceLists: values may arrive as k8s
    quantity strings ("100m", "1Gi")."""
    return (
        typing.get_origin(tp) is dict
        and typing.get_args(tp) == (str, float)
    )


def from_k8s_dict(cls, data):
    """Build `cls` from a camelCase k8s JSON dict. Unknown keys are ignored
    (server-side pruning analog); missing keys take dataclass defaults."""
    if data is None:
        return None
    tp = _strip_optional(cls)
    origin = typing.get_origin(tp)
    if origin is list:
        (item_tp,) = typing.get_args(tp)
        return [from_k8s_dict(item_tp, item) for item in data]
    if origin is dict:
        key_tp, val_tp = typing.get_args(tp)
        if val_tp is float:
            return {k: _to_float(v) for k, v in data.items()}
        return {k: from_k8s_dict(val_tp, v) for k, v in data.items()}
    if dataclasses.is_dataclass(tp):
        hints = typing.get_type_hints(tp)
        wrap = getattr(tp, "_WIRE_WRAP", None)
        kwargs = {}
        for f in dataclasses.fields(tp):
            wire = _wire_name(tp, f.name)
            if wire in data:
                raw = data[wire]
            elif f.name in data:
                raw = data[f.name]
            else:
                continue
            if wrap and f.name in wrap and isinstance(raw, dict):
                # wire wraps the list in an object (e.g. NodeAffinity's
                # required is a NodeSelector{nodeSelectorTerms: [...]})
                raw = raw.get(wrap[f.name], [])
            if _is_time_field(f.name) and raw is not None:
                kwargs[f.name] = _parse_time(raw)
            else:
                kwargs[f.name] = from_k8s_dict(hints[f.name], raw)
        return tp(**kwargs)
    if tp is float:
        return _to_float(data)
    if tp in (int, str, bool):
        return data
    return data  # Any / plain dict (e.g. provider config)


def _to_float(v) -> float:
    if isinstance(v, str):
        return parse_quantity(v)
    return float(v)


def to_k8s_dict(obj):
    """Serialize an object-model instance to a camelCase k8s JSON dict.
    Empty lists/dicts/None are dropped (k8s omitempty semantics)."""
    if obj is None:
        return None
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        wrap = getattr(type(obj), "_WIRE_WRAP", None)
        for f in dataclasses.fields(obj):
            value = getattr(obj, f.name)
            if _is_time_field(f.name) and isinstance(value, (int, float)):
                # zero means unset in the object model: OMIT it rather than
                # emit a bare float a real apiserver would reject for a
                # Time/MicroTime field
                encoded = _format_time(value) if value else None
            else:
                encoded = to_k8s_dict(value)
            if encoded in (None, [], {}, ""):
                continue
            if wrap and f.name in wrap:
                encoded = {wrap[f.name]: encoded}
            out[_wire_name(type(obj), f.name)] = encoded
        return out
    if isinstance(obj, list):
        return [to_k8s_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_k8s_dict(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return list(obj)
    return obj
