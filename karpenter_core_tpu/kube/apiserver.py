"""Real-cluster kube client: the InMemoryKubeClient surface over a live
kube-apiserver's REST API.

The control plane consumes only the narrow client surface in
kube/client.py (get/list/create/update/compare_and_update/apply/delete/
finalize/watch + conflict semantics). This adapter implements that surface
against an actual apiserver — the deployment story the Helm charts
describe (reference equivalents: client-go via controller-runtime,
pkg/operator/operator.go:106-123, pkg/test/environment.go:69-118).

No external kubernetes package is required: objects convert through
kube/serialization.py and HTTP rides urllib. The transport is injectable,
so tests drive the full adapter against a mocked apiserver; in-cluster
config (service-account token + CA) is detected automatically.
"""
from __future__ import annotations

import json
import queue
import random
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu import chaos
from karpenter_core_tpu.kube.client import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    _kind_of,
)
from karpenter_core_tpu.kube.serialization import from_k8s_dict, to_k8s_dict
from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs.log import get_logger

LOG = get_logger("karpenter.kube")

KUBE_TRANSPORT_RETRIES = REGISTRY.counter(
    f"{NAMESPACE}_kube_transport_retries_total",
    "Apiserver requests retried after a transient transport failure "
    "(5xx/429/timeout/connection-reset), by HTTP method",
)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind -> (api prefix, plural, namespaced)
RESOURCES: Dict[str, Tuple[str, str, bool]] = {
    "Pod": ("/api/v1", "pods", True),
    "Node": ("/api/v1", "nodes", False),
    "Namespace": ("/api/v1", "namespaces", False),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "Secret": ("/api/v1", "secrets", True),
    "PersistentVolumeClaim": ("/api/v1", "persistentvolumeclaims", True),
    "PersistentVolume": ("/api/v1", "persistentvolumes", False),
    "StorageClass": ("/apis/storage.k8s.io/v1", "storageclasses", False),
    "CSINode": ("/apis/storage.k8s.io/v1", "csinodes", False),
    "PodDisruptionBudget": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "DaemonSet": ("/apis/apps/v1", "daemonsets", True),
    "Provisioner": ("/apis/karpenter.sh/v1alpha5", "provisioners", False),
    "Machine": ("/apis/karpenter.sh/v1alpha5", "machines", False),
    "Event": ("/api/v1", "events", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
}

API_VERSIONS = {
    "Provisioner": "karpenter.sh/v1alpha5",
    "Machine": "karpenter.sh/v1alpha5",
    "StorageClass": "storage.k8s.io/v1",
    "CSINode": "storage.k8s.io/v1",
    "PodDisruptionBudget": "policy/v1",
    "DaemonSet": "apps/v1",
    "Lease": "coordination.k8s.io/v1",
}


class UrllibTransport:
    """Default transport: urllib with bearer-token + CA from the in-cluster
    service account (or explicit kwargs)."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_cert: Optional[str] = None, insecure: bool = False):
        self.base_url = base_url.rstrip("/")
        if token is None:
            try:
                token = open(f"{SA_DIR}/token").read().strip()
            except OSError:
                token = ""
        self.token = token
        if ca_cert is None:
            import os

            default_ca = f"{SA_DIR}/ca.crt"
            ca_cert = default_ca if os.path.exists(default_ca) else None
        if insecure:
            self.ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self.ctx.check_hostname = False
            self.ctx.verify_mode = ssl.CERT_NONE
        elif ca_cert:
            self.ctx = ssl.create_default_context(cafile=ca_cert)
        else:
            self.ctx = ssl.create_default_context()

    def __call__(self, method: str, path: str, body: Optional[dict] = None,
                 params: Optional[dict] = None, stream: bool = False,
                 timeout: float = 30.0):
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            resp = urllib.request.urlopen(
                req, context=self.ctx if url.startswith("https") else None,
                timeout=None if stream else timeout,
            )
        except urllib.error.HTTPError as e:
            # headers ride along so the retry layer can honor Retry-After
            return e.code, e.read().decode(errors="replace"), dict(e.headers)
        if stream:
            return resp.status, resp  # caller iterates the body
        return resp.status, resp.read().decode()


# HTTP statuses the retry layer treats as transient. 409 is deliberately
# absent: a conflict is a SEMANTIC outcome (optimistic concurrency) the
# callers' rebase logic owns — blind retry of the same stale PUT can never
# succeed and would just burn the conflict window.
TRANSIENT_HTTP = frozenset({429, 500, 502, 503, 504})
# non-idempotent verbs retry a NARROWER set: 429/503 are pre-processing
# rejections the apiserver itself sends (the request was not applied), but
# 500/502/504 can come from a gateway AFTER the apiserver committed the
# write — replaying an applied POST/DELETE turns success into a spurious
# AlreadyExists/NotFound (client-go draws the same idempotency line)
TRANSIENT_HTTP_NON_IDEMPOTENT = frozenset({429, 503})


class ApiServerKubeClient:
    """InMemoryKubeClient-compatible adapter over a live apiserver.

    Every non-streaming request rides a bounded retry loop: transient
    transport failures (connection reset, timeout, 5xx, 429) back off
    exponentially with full jitter and honor Retry-After — the client-go
    rest.Client retry posture — so a blipping apiserver degrades a
    reconcile's latency instead of failing it."""

    def __init__(self, transport, scheme=None, default_namespace: str = "default",
                 retry_attempts: int = 4, retry_base: float = 0.1,
                 retry_max: float = 2.0, rng: Optional[random.Random] = None):
        from karpenter_core_tpu.api.scheme import default_scheme

        self.transport = transport
        self.scheme = scheme or default_scheme()
        self.default_namespace = default_namespace
        self.retry_attempts = retry_attempts
        self.retry_base = retry_base
        self.retry_max = retry_max
        self._rng = rng or random.Random()
        self._watch_threads: List[threading.Thread] = []
        self._watch_cancels: Dict[int, threading.Event] = {}
        self._watch_mu = threading.Lock()
        self._stop = threading.Event()

    # -- transport with transient-failure retries ---------------------------

    def _backoff(self, attempt: int, retry_after: Optional[str]) -> float:
        """Exponential with full jitter (utils/backoff — N controllers
        retrying the same blip must not re-land in lockstep); a parseable
        Retry-After wins, capped at retry_max."""
        if retry_after:
            try:
                return min(float(retry_after), self.retry_max)
            except ValueError:
                pass  # HTTP-date form: fall through to the backoff
        from karpenter_core_tpu.utils.backoff import full_jitter

        return full_jitter(attempt, self.retry_base, self.retry_max, self._rng)

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 params: Optional[dict] = None, timeout: float = 30.0,
                 transient: Optional[frozenset] = None):
        """One logical request; returns (status, body). Retries transient
        failures; conflicts (409) and other 4xx return to the caller
        untouched. `transient` narrows the retriable statuses for calls
        whose semantics claim one of them (eviction's PDB 429); by default
        it is the full set for GET and the not-applied-only subset for
        write verbs (see TRANSIENT_HTTP_NON_IDEMPOTENT)."""
        if transient is None:
            transient = (
                TRANSIENT_HTTP if method == "GET"
                else TRANSIENT_HTTP_NON_IDEMPOTENT
            )
        attempt = 0
        while True:
            retry_after = None
            try:
                # chaos hook: the edge every apiserver round trip crosses;
                # injected faults exercise THIS retry loop
                chaos.maybe_fail(chaos.KUBE_TRANSPORT)
                result = self.transport(
                    method, path, body, params=params, timeout=timeout
                )
            except (ConnectionError, TimeoutError, OSError) as e:
                # urllib.error.URLError (and socket.timeout) subclass
                # OSError: connection refused/reset, DNS blips, timeouts.
                # AMBIGUOUS failures (the request may have been applied
                # before the connection died) are only retried for GET —
                # replaying a POST/DELETE whose first copy landed turns a
                # server-side success into a spurious AlreadyExists/
                # NotFound (client-go retries connection errors for
                # idempotent verbs only). A rejected-with-status request
                # (the branch below) was NOT applied, so those retry for
                # every verb.
                if method != "GET" or attempt >= self.retry_attempts:
                    raise
                status = None
            else:
                status, resp_body = result[0], result[1]
                headers = result[2] if len(result) > 2 else {}
                if status not in transient or attempt >= self.retry_attempts:
                    return status, resp_body
                retry_after = {
                    k.lower(): v for k, v in (headers or {}).items()
                }.get("retry-after")
            KUBE_TRANSPORT_RETRIES.inc({"method": method})
            delay = self._backoff(attempt, retry_after)
            attempt += 1
            # correlated retry trail: inside a reconcile the bound
            # controller/reconcile-id fields (obs/log) ride along, so a
            # blipping apiserver shows up attributed, not anonymous
            LOG.warning(
                "kube transport retry", method=method, path=path,
                status=status, attempt=attempt, delay_s=round(delay, 3),
            )
            if delay > 0:
                time.sleep(delay)

    @classmethod
    def in_cluster(cls, **kwargs):
        from karpenter_core_tpu.obs import envflags

        host = envflags.raw("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = envflags.raw("KUBERNETES_SERVICE_PORT", "443")
        return cls(UrllibTransport(f"https://{host}:{port}"), **kwargs)

    # -- path/encoding helpers ---------------------------------------------

    def _path(self, kind: str, namespace: str = "", name: str = "") -> str:
        prefix, plural, namespaced = RESOURCES[kind]
        path = prefix
        if namespaced:
            path += f"/namespaces/{namespace or self.default_namespace}"
        path += f"/{plural}"
        if name:
            path += f"/{name}"
        return path

    def _cls(self, kind: str):
        return self.scheme.type_for(kind)

    def _decode(self, kind: str, raw: dict):
        obj = from_k8s_dict(self._cls(kind), raw)
        rv = (raw.get("metadata") or {}).get("resourceVersion")
        if rv is not None:
            try:
                obj.metadata.resource_version = int(rv)
            except (TypeError, ValueError):
                obj.metadata.resource_version = 0
        return obj

    def _encode(self, obj) -> dict:
        kind = _kind_of(obj)
        raw = to_k8s_dict(obj)
        raw["kind"] = kind
        raw["apiVersion"] = API_VERSIONS.get(kind, "v1")
        meta = raw.setdefault("metadata", {})
        rv = meta.pop("resourceVersion", None)
        if rv:
            meta["resourceVersion"] = str(rv)
        prefix, _, namespaced = RESOURCES[kind]
        if not namespaced:
            meta.pop("namespace", None)
        elif not meta.get("namespace"):
            meta["namespace"] = self.default_namespace
        return raw

    # -- the client surface (kube/client.py parity) -------------------------

    def new_object(self, kind: str):
        return self._cls(kind)()

    def create(self, obj):
        kind = _kind_of(obj)
        ns = getattr(obj.metadata, "namespace", "")
        status, body = self._request("POST", self._path(kind, ns), self._encode(obj))
        if status == 409:
            raise AlreadyExistsError(f"{kind} {obj.metadata.name} already exists")
        self._raise_for(status, body, kind, obj.metadata.name)
        return self._decode(kind, json.loads(body))

    def get(self, kind: str, namespace: str, name: str):
        status, body = self._request("GET", self._path(kind, namespace, name))
        if status == 404:
            return None
        self._raise_for(status, body, kind, name)
        return self._decode(kind, json.loads(body))

    def update(self, obj):
        kind = _kind_of(obj)
        ns = getattr(obj.metadata, "namespace", "")
        status, body = self._request(
            "PUT", self._path(kind, ns, obj.metadata.name), self._encode(obj)
        )
        if status == 409:
            raise ConflictError(f"{kind} {obj.metadata.name} resource version conflict")
        if status == 404:
            raise NotFoundError(f"{kind} {obj.metadata.name} not found")
        self._raise_for(status, body, kind, obj.metadata.name)
        return self._decode(kind, json.loads(body))

    def compare_and_update(self, obj, expected_rv: int):
        obj.metadata.resource_version = expected_rv
        return self.update(obj)

    def update_status(self, obj):
        """PUT to the status SUBRESOURCE — the CRDs declare
        `subresources: {status: {}}`, so a plain PUT silently drops status
        changes; every controller status write (machine conditions,
        counter's status.resources) must land here (reference:
        Status().Patch, counter/controller.go:67).

        Like the reference's status Patch, a concurrent spec/metadata bump
        must not fail the status write: on 409 the current resourceVersion
        is re-read once and the write retried (a /status PUT only persists
        status, so rebasing is always safe)."""
        kind = _kind_of(obj)
        ns = getattr(obj.metadata, "namespace", "")
        path = self._path(kind, ns, obj.metadata.name) + "/status"
        status, body = self._request("PUT", path, self._encode(obj))
        if status == 409:
            current = self.get(kind, ns, obj.metadata.name)
            if current is None:
                raise NotFoundError(f"{kind} {obj.metadata.name} not found")
            obj.metadata.resource_version = current.metadata.resource_version
            status, body = self._request("PUT", path, self._encode(obj))
            if status == 409:
                raise ConflictError(
                    f"{kind} {obj.metadata.name} resource version conflict"
                )
        if status == 404:
            raise NotFoundError(f"{kind} {obj.metadata.name} not found")
        self._raise_for(status, body, kind, obj.metadata.name)
        return self._decode(kind, json.loads(body))

    def evict(self, namespace: str, name: str) -> None:
        """POST the pods/eviction subresource; a 429 (PDB exhausted) raises
        EvictionBlockedError so the eviction queue requeues with backoff —
        server-enforced budgets instead of a host-side TOCTOU check
        (reference eviction.go:111-124)."""
        from karpenter_core_tpu.kube.client import EvictionBlockedError

        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        # a 429 here is SEMANTIC (the PDB has no disruptions left), not a
        # rate limit: retrying at the transport layer would burn seconds
        # per blocked eviction — the eviction queue owns the requeue
        status, resp = self._request(
            "POST", self._path("Pod", namespace, name) + "/eviction", body,
            transient=TRANSIENT_HTTP_NON_IDEMPOTENT - {429},
        )
        if status == 404:
            return  # already gone: success
        if status == 429:
            raise EvictionBlockedError(str(resp)[:200])
        self._raise_for(status, resp, "Pod", name)

    def apply(self, obj):
        try:
            return self.create(obj)
        except AlreadyExistsError:
            kind = _kind_of(obj)
            current = self.get(kind, getattr(obj.metadata, "namespace", ""), obj.metadata.name)
            if current is not None:
                obj.metadata.resource_version = current.metadata.resource_version
            return self.update(obj)

    def delete(self, obj_or_kind, namespace: str = None, name: str = None):
        if isinstance(obj_or_kind, str):
            kind = obj_or_kind
        else:
            kind = _kind_of(obj_or_kind)
            namespace = getattr(obj_or_kind.metadata, "namespace", "")
            name = obj_or_kind.metadata.name
        status, body = self._request("DELETE", self._path(kind, namespace or "", name))
        if status == 404:
            raise NotFoundError(f"{kind} {name} not found")
        self._raise_for(status, body, kind, name)

    def finalize(self, obj):
        """Persist finalizer removal so the apiserver completes deletion."""
        self.update(obj)

    # page size for chunked LISTs — a 50k-pod cluster's apiserver will not
    # return one 50k-item response; every page after the first rides the
    # server's `continue` token (client-go's default chunk size is 500)
    LIST_LIMIT = 500

    def list(self, kind: str, namespace: str = None, selector=None,
             field_filter=None, copy_objects: bool = True) -> List[object]:
        # copy_objects is part of the client surface; decoded REST objects
        # are always fresh, so it has no effect here
        prefix, plural, namespaced = RESOURCES[kind]
        if namespaced and namespace:
            path = f"{prefix}/namespaces/{namespace}/{plural}"
        else:
            path = f"{prefix}/{plural}"
        items: List[object] = []
        params = {"limit": str(self.LIST_LIMIT)}
        while True:
            status, body = self._request("GET", path, params=params)
            if status == 410 and "continue" in params:
                # the snapshot behind the continue token expired (etcd
                # compaction mid-pagination on a large cluster): fall back
                # to ONE unpaginated full list, like client-go's ListPager
                status, body = self._request("GET", path)
                self._raise_for(status, body, kind, "")
                items = [
                    self._decode(kind, raw)
                    for raw in json.loads(body).get("items", [])
                ]
                break
            self._raise_for(status, body, kind, "")
            page = json.loads(body)
            items.extend(self._decode(kind, raw) for raw in page.get("items", []))
            token = (page.get("metadata") or {}).get("continue")
            if not token:
                break
            params = {"limit": str(self.LIST_LIMIT), "continue": token}
        if selector is not None:
            items = [o for o in items if selector.matches(o.metadata.labels)]
        if field_filter is not None:
            items = [o for o in items if field_filter(o)]
        return items

    def namespaces(self) -> List[str]:
        return [n.metadata.name for n in self.list("Namespace")]

    # -- watches ------------------------------------------------------------

    def watch(self, kind: str, backlog: bool = True) -> "queue.Queue":
        """Streamed apiserver watch pumped into a queue of (event, obj),
        matching the in-memory client's contract.

        Reconnects resume from the last seen resourceVersion; when that is
        rejected (410 Gone / stream error) the pump RELISTS, replaying
        current objects as ADDED and emitting synthetic DELETED events for
        objects that vanished while the stream was down — the informer
        list-then-watch contract, so consumers never hold ghosts."""
        q: "queue.Queue" = queue.Queue()
        known: dict = {}  # (namespace, name) -> True, for deletion diffing
        last_rv = {"v": None}
        # per-watch cancellation: unwatch() sets this so a relisting
        # consumer (the operator's stale-stream recovery) can retire the
        # old pump instead of leaking a thread + stream + orphan queue
        cancel = threading.Event()
        with self._watch_mu:
            self._watch_cancels[id(q)] = cancel

        def stopped() -> bool:
            return self._stop.is_set() or cancel.is_set()

        def relist():
            current = {}
            for obj in self.list(kind):
                key = (getattr(obj.metadata, "namespace", ""), obj.metadata.name)
                current[key] = True
                q.put(("ADDED", obj))
                rv = obj.metadata.resource_version
                if rv:
                    last_rv["v"] = max(int(last_rv["v"] or 0), int(rv))
            for key in list(known):
                if key not in current:
                    gone = self.new_object(kind)
                    gone.metadata.namespace, gone.metadata.name = key
                    q.put(("DELETED", gone))
            known.clear()
            known.update(current)

        if backlog:
            relist()

        def pump():
            fresh = backlog  # initial list already ran when backlog=True
            while not stopped():
                try:
                    if not fresh:
                        relist()
                    fresh = False
                    params = {"watch": "true"}
                    if last_rv["v"] is not None:
                        params["resourceVersion"] = str(last_rv["v"])
                    result = self.transport(
                        "GET", self._path(kind), params=params, stream=True
                    )
                    status, resp = result[0], result[1]  # HTTPError adds headers
                    if status != 200:
                        last_rv["v"] = None  # rv too old; force a relist
                        cancel.wait(2.0)  # (global stop re-checked above)
                        continue
                    for line in resp:
                        if stopped():
                            return
                        if not line.strip():
                            continue
                        event = json.loads(line)
                        etype = event.get("type", "MODIFIED")
                        obj = self._decode(kind, event.get("object", {}))
                        key = (getattr(obj.metadata, "namespace", ""),
                               obj.metadata.name)
                        if etype == "DELETED":
                            known.pop(key, None)
                        else:
                            known[key] = True
                        rv = obj.metadata.resource_version
                        if rv:
                            last_rv["v"] = max(int(last_rv["v"] or 0), int(rv))
                        q.put((etype, obj))
                except Exception:
                    cancel.wait(2.0)  # stream dropped; relist on retry

        t = threading.Thread(target=pump, daemon=True, name=f"apiserver-watch-{kind}")
        t.start()
        self._watch_threads.append(t)
        return q

    def unwatch(self, kind: str, q) -> None:
        """Retire the queue's pump: its thread exits at the next event,
        stream error, or reconnect attempt — a relisting consumer swapping
        queues must not accumulate live pumps (best-effort: a pump blocked
        mid-stream lingers until the stream next yields or drops)."""
        with self._watch_mu:
            cancel = self._watch_cancels.pop(id(q), None)
        if cancel is not None:
            cancel.set()

    def close(self) -> None:
        self._stop.set()

    # -- error mapping -------------------------------------------------------

    @staticmethod
    def _raise_for(status: int, body, kind: str, name: str) -> None:
        if status >= 400:
            raise RuntimeError(f"apiserver {status} for {kind} {name}: {str(body)[:200]}")
