"""In-memory kube apiserver analog.

The reference tests against a real apiserver via envtest
(pkg/test/environment.go:69-118); this framework has no cluster dependency, so
the object store + list/watch semantics live in-process. Controllers consume
the same get/list/create/update/delete/watch surface that client-go provides.

Thread-safe; watches deliver (event_type, object) tuples to subscriber queues.
"""
from __future__ import annotations

import copy
import queue
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from karpenter_core_tpu import chaos
from karpenter_core_tpu.kube.objects import LabelSelector, NamespacedName

WatchEvent = Tuple[str, object]  # ("ADDED"|"MODIFIED"|"DELETED", obj)


class ConflictError(Exception):
    """Resource-version conflict on update."""


class NotFoundError(Exception):
    pass


class AlreadyExistsError(Exception):
    pass


class EvictionBlockedError(Exception):
    """pods/eviction returned 429: a PodDisruptionBudget blocks the eviction
    (server-side enforcement, reference eviction.go:111-124). Callers requeue
    with backoff."""


# kinds served with a status SUBRESOURCE: a plain PUT to the main resource
# silently drops status changes (the apiserver contract the shipped CRDs
# declare via `subresources: {status: {}}`); status persists only through
# update_status(). Core Pod/Node behave the same on a real apiserver.
STATUS_SUBRESOURCE_KINDS = frozenset({"Machine", "Provisioner", "Node", "Pod"})


def _kind_of(obj) -> str:
    return type(obj).__name__


class InMemoryKubeClient:
    """Object store keyed (kind, namespace, name) with watch fan-out.

    `scheme` (api/scheme.default_scheme) maps kind names to types —
    new_object() constructs through it, and strict=True rejects writes of
    unregistered kinds (the runtime.Scheme contract, operator/scheme)."""

    def __init__(self, scheme=None, strict: bool = False):
        self._mu = threading.RLock()
        self._objects: Dict[str, Dict[NamespacedName, object]] = {}
        self._watchers: Dict[str, List[queue.Queue]] = {}
        self._rv = 0
        if scheme is None:
            from karpenter_core_tpu.api.scheme import default_scheme

            scheme = default_scheme()
        self.scheme = scheme
        self.strict = strict

    def new_object(self, kind: str):
        return self.scheme.new_object(kind)

    # -- CRUD -------------------------------------------------------------

    def create(self, obj) -> object:
        chaos.maybe_fail(chaos.KUBE_TRANSPORT)
        kind = _kind_of(obj)
        if self.strict and not self.scheme.recognizes(kind):
            raise TypeError(f"kind {kind} is not registered in the scheme")
        with self._mu:
            key = NamespacedName(obj.metadata.namespace, obj.metadata.name)
            store = self._objects.setdefault(kind, {})
            if key in store:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            stored = copy.deepcopy(obj)
            store[key] = stored
            self._notify(kind, "ADDED", stored)
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Optional[object]:
        chaos.maybe_fail(chaos.KUBE_TRANSPORT)
        with self._mu:
            obj = self._objects.get(kind, {}).get(NamespacedName(namespace, name))
            return copy.deepcopy(obj) if obj is not None else None

    def update(self, obj) -> object:
        chaos.maybe_fail(chaos.KUBE_TRANSPORT)
        kind = _kind_of(obj)
        with self._mu:
            key = NamespacedName(obj.metadata.namespace, obj.metadata.name)
            store = self._objects.setdefault(kind, {})
            if key not in store:
                raise NotFoundError(f"{kind} {key} not found")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            stored = copy.deepcopy(obj)
            if kind in STATUS_SUBRESOURCE_KINDS and hasattr(stored, "status"):
                # subresource contract: plain PUT silently drops status
                # changes (controllers must Status().Update —
                # counter/controller.go:67); create() keeps seeded status so
                # test fixtures that are "born with" capacity keep working
                stored.status = copy.deepcopy(store[key].status)
            store[key] = stored
            self._notify(kind, "MODIFIED", stored)
            return copy.deepcopy(stored)

    def update_status(self, obj) -> object:
        """PUT to the status subresource: persists ONLY obj.status (spec and
        metadata of the stored object are untouched, mirroring the apiserver,
        which ignores everything but status on /status writes)."""
        chaos.maybe_fail(chaos.KUBE_TRANSPORT)
        kind = _kind_of(obj)
        with self._mu:
            key = NamespacedName(obj.metadata.namespace, obj.metadata.name)
            store = self._objects.setdefault(kind, {})
            if key not in store:
                raise NotFoundError(f"{kind} {key} not found")
            # fresh deepcopy into the store (same as update/create): watchers
            # holding a previously-notified reference must not observe this
            # write mutating it underneath them
            stored = copy.deepcopy(store[key])
            self._rv += 1
            stored.metadata.resource_version = self._rv
            obj.metadata.resource_version = self._rv
            stored.status = copy.deepcopy(obj.status)
            store[key] = stored
            self._notify(kind, "MODIFIED", stored)
            return copy.deepcopy(stored)

    def compare_and_update(self, obj, expected_rv: int) -> object:
        """Optimistic-concurrency update: raises ConflictError unless the
        stored resource_version still equals expected_rv — the apiserver's
        409 contract. Lease-based leader election depends on this to
        arbitrate between processes."""
        kind = _kind_of(obj)
        with self._mu:
            key = NamespacedName(obj.metadata.namespace, obj.metadata.name)
            store = self._objects.setdefault(kind, {})
            cur = store.get(key)
            if cur is None:
                raise NotFoundError(f"{kind} {key} not found")
            if cur.metadata.resource_version != expected_rv:
                raise ConflictError(
                    f"{kind} {key} resource_version "
                    f"{cur.metadata.resource_version} != expected {expected_rv}"
                )
            self._rv += 1
            obj.metadata.resource_version = self._rv
            stored = copy.deepcopy(obj)
            store[key] = stored
            self._notify(kind, "MODIFIED", stored)
            return copy.deepcopy(stored)

    def apply(self, obj) -> object:
        """Create-or-update."""
        kind = _kind_of(obj)
        with self._mu:
            key = NamespacedName(obj.metadata.namespace, obj.metadata.name)
            if key in self._objects.get(kind, {}):
                return self.update(obj)
            return self.create(obj)

    def delete(self, obj_or_kind, namespace: str = None, name: str = None) -> None:
        """delete(obj) or delete(kind, namespace, name).

        Honors finalizers: sets deletion_timestamp and emits MODIFIED until the
        finalizer list is empty, then removes — mirrors apiserver behavior the
        termination/machine controllers depend on.
        """
        chaos.maybe_fail(chaos.KUBE_TRANSPORT)
        if isinstance(obj_or_kind, str):
            kind = obj_or_kind
        else:
            kind = _kind_of(obj_or_kind)
            namespace = obj_or_kind.metadata.namespace
            name = obj_or_kind.metadata.name
        with self._mu:
            key = NamespacedName(namespace, name)
            store = self._objects.get(kind, {})
            existing = store.get(key)
            if existing is None:
                raise NotFoundError(f"{kind} {key} not found")
            if existing.metadata.finalizers:
                if existing.metadata.deletion_timestamp is None:
                    existing.metadata.deletion_timestamp = time.time()
                    self._rv += 1
                    existing.metadata.resource_version = self._rv
                    self._notify(kind, "MODIFIED", existing)
                return
            del store[key]
            self._notify(kind, "DELETED", existing)

    def evict(self, namespace: str, name: str) -> None:
        """POST pods/eviction analog with SERVER-side PDB enforcement
        (eviction.go:111-124): raises EvictionBlockedError (the 429) when a
        matching PodDisruptionBudget has no disruptions left, else deletes
        the pod and decrements the budget — so concurrent PDB consumers
        can't over-evict through a check-then-delete race. A missing pod is
        success (it is already gone)."""
        with self._mu:
            pod = self._objects.get("Pod", {}).get(NamespacedName(namespace, name))
            if pod is None:
                return
            matching = [
                pdb
                for pdb in self._objects.get("PodDisruptionBudget", {}).values()
                if pdb.spec.selector is not None
                and pdb.metadata.namespace == namespace
                and pdb.spec.selector.matches(pod.metadata.labels)
            ]
            if len(matching) > 1:
                # the real eviction API refuses when >1 PDB covers a pod
                # (it cannot atomically update multiple budgets)
                raise EvictionBlockedError(
                    f"This pod has more than one PodDisruptionBudget: "
                    f"{', '.join(p.metadata.name for p in matching)}"
                )
            if matching:
                pdb = matching[0]
                if pdb.status.disruptions_allowed <= 0:
                    raise EvictionBlockedError(
                        f"Cannot evict pod as it would violate the pod's "
                        f"disruption budget "
                        f"{pdb.metadata.namespace}/{pdb.metadata.name}"
                    )
                pdb.status.disruptions_allowed -= 1
            self.delete("Pod", namespace, name)

    def finalize(self, obj) -> None:
        """Persist a finalizer removal; completes deletion if terminating."""
        kind = _kind_of(obj)
        with self._mu:
            key = NamespacedName(obj.metadata.namespace, obj.metadata.name)
            store = self._objects.get(kind, {})
            existing = store.get(key)
            if existing is None:
                raise NotFoundError(f"{kind} {key} not found")
            existing.metadata.finalizers = list(obj.metadata.finalizers)
            if existing.metadata.deletion_timestamp is not None and not existing.metadata.finalizers:
                del store[key]
                self._notify(kind, "DELETED", existing)
            else:
                self._rv += 1
                existing.metadata.resource_version = self._rv
                obj.metadata.resource_version = self._rv
                self._notify(kind, "MODIFIED", existing)

    # -- queries ----------------------------------------------------------

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[LabelSelector] = None,
        field_filter: Optional[Callable[[object], bool]] = None,
        copy_objects: bool = True,
    ) -> List[object]:
        """copy_objects=False returns SHARED references (the informer-cache
        read idiom client-go consumers use): only for read-only paths —
        callers that mutate must deep-copy first, exactly as they must with
        objects handed out by a controller-runtime cache. The deprovisioning
        replan reads thousands of pods per cycle; cloning them dominated
        the whole ladder's host time."""
        chaos.maybe_fail(chaos.KUBE_TRANSPORT)
        with self._mu:
            out = []
            for key, obj in self._objects.get(kind, {}).items():
                if namespace is not None and key.namespace != namespace:
                    continue
                if selector is not None and not selector.matches(obj.metadata.labels):
                    continue
                if field_filter is not None and not field_filter(obj):
                    continue
                out.append(copy.deepcopy(obj) if copy_objects else obj)
            return out

    def namespaces(self) -> List[str]:
        with self._mu:
            names = {o.metadata.name for o in self._objects.get("Namespace", {}).values()}
            for kind_store in self._objects.values():
                for key in kind_store:
                    if key.namespace:
                        names.add(key.namespace)
            return sorted(names)

    # -- watches ----------------------------------------------------------

    def watch(self, kind: str, backlog: bool = True) -> queue.Queue:
        """Subscribe to a kind; returns a queue of WatchEvents. With backlog,
        current objects are replayed as ADDED."""
        q: queue.Queue = queue.Queue()
        with self._mu:
            if backlog:
                for obj in self._objects.get(kind, {}).values():
                    q.put(("ADDED", copy.deepcopy(obj)))
            self._watchers.setdefault(kind, []).append(q)
        return q

    def unwatch(self, kind: str, q: queue.Queue) -> None:
        with self._mu:
            if q in self._watchers.get(kind, []):
                self._watchers[kind].remove(q)

    def _notify(self, kind: str, event: str, obj) -> None:
        for q in self._watchers.get(kind, []):
            q.put((event, copy.deepcopy(obj)))
