"""Test object builders — the analog of reference pkg/test (pods.go etc.)."""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from karpenter_core_tpu.api.provisioner import (
    Consolidation,
    Limits,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_core_tpu.kube.objects import (
    Affinity,
    Condition,
    Container,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.utils.resources import parse_resource_list

_counter = itertools.count(1)


class FakeClock:
    """Steppable clock (the analog of clock/testing.FakeClock the reference
    threads through every TTL-sensitive controller).

    `sleep` gives TTL waits real semantics under test: it blocks while
    another thread drives the clock forward with `advance` (so the 15s
    revalidation window is genuinely exercised, validation.go:60-67), but
    if the clock sits still for `grace` real seconds — no stepper thread —
    it jumps itself to the deadline instead of deadlocking the test."""

    def __init__(self, t: float = 1_700_000_000.0, grace: float = 0.05):
        import threading

        self._t = t
        self._grace = grace
        self._cond = threading.Condition()

    @property
    def t(self) -> float:
        return self._t

    def __call__(self) -> float:
        with self._cond:
            return self._t

    def advance(self, seconds: float) -> None:
        with self._cond:
            self._t += seconds
            self._cond.notify_all()

    def sleep(self, seconds: float) -> None:
        with self._cond:
            deadline = self._t + seconds
            while self._t < deadline:
                last = self._t
                self._cond.wait(timeout=self._grace)
                if self._t == last:  # nobody is stepping: jump
                    self._t = deadline
                    self._cond.notify_all()


def unique_name(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_counter)}"


def make_pod(
    name: Optional[str] = None,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    requests: Optional[Dict[str, object]] = None,
    limits: Optional[Dict[str, object]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    node_name: str = "",
    tolerations: Optional[List[Toleration]] = None,
    topology_spread: Optional[List[TopologySpreadConstraint]] = None,
    pod_affinity_required: Optional[List[PodAffinityTerm]] = None,
    pod_affinity_preferred: Optional[List[WeightedPodAffinityTerm]] = None,
    pod_anti_affinity_required: Optional[List[PodAffinityTerm]] = None,
    pod_anti_affinity_preferred: Optional[List[WeightedPodAffinityTerm]] = None,
    node_affinity_required: Optional[List[NodeSelectorTerm]] = None,
    node_affinity_preferred=None,
    host_ports: Optional[List[int]] = None,
    owner_kind: str = "",
    phase: str = "Pending",
    unschedulable: bool = True,
) -> Pod:
    """A pending, unschedulable pod by default (marked with the PodScheduled
    Unschedulable condition like GetPendingPods expects)."""
    containers = [
        Container(
            resources=ResourceRequirements(
                requests=parse_resource_list(requests or {}),
                limits=parse_resource_list(limits or {}),
            ),
            ports=[ContainerPort(host_port=p) for p in (host_ports or [])],
        )
    ]
    affinity = None
    if any(
        [
            pod_affinity_required,
            pod_affinity_preferred,
            pod_anti_affinity_required,
            pod_anti_affinity_preferred,
            node_affinity_required,
            node_affinity_preferred,
        ]
    ):
        affinity = Affinity(
            node_affinity=NodeAffinity(
                required=list(node_affinity_required or []),
                preferred=list(node_affinity_preferred or []),
            )
            if (node_affinity_required or node_affinity_preferred)
            else None,
            pod_affinity=PodAffinity(
                required=list(pod_affinity_required or []),
                preferred=list(pod_affinity_preferred or []),
            )
            if (pod_affinity_required or pod_affinity_preferred)
            else None,
            pod_anti_affinity=PodAntiAffinity(
                required=list(pod_anti_affinity_required or []),
                preferred=list(pod_anti_affinity_preferred or []),
            )
            if (pod_anti_affinity_required or pod_anti_affinity_preferred)
            else None,
        )
    pod = Pod(
        metadata=ObjectMeta(
            name=name or unique_name("pod"),
            namespace=namespace,
            labels=dict(labels or {}),
            annotations=dict(annotations or {}),
        ),
        spec=PodSpec(
            node_name=node_name,
            node_selector=dict(node_selector or {}),
            affinity=affinity,
            tolerations=list(tolerations or []),
            containers=containers,
            topology_spread_constraints=list(topology_spread or []),
        ),
    )
    pod.status.phase = phase
    if unschedulable and not node_name:
        pod.status.conditions.append(
            Condition(type="PodScheduled", status="False", reason="Unschedulable")
        )
    if owner_kind:
        pod.metadata.owner_references.append(OwnerReference(kind=owner_kind, name="owner"))
    return pod


def make_provisioner(
    name: Optional[str] = None,
    requirements: Optional[List[NodeSelectorRequirement]] = None,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    startup_taints: Optional[List[Taint]] = None,
    limits: Optional[Dict[str, object]] = None,
    weight: Optional[int] = None,
    ttl_seconds_after_empty: Optional[int] = None,
    ttl_seconds_until_expired: Optional[int] = None,
    consolidation_enabled: Optional[bool] = None,
) -> Provisioner:
    spec = ProvisionerSpec(
        requirements=list(requirements or []),
        labels=dict(labels or {}),
        taints=list(taints or []),
        startup_taints=list(startup_taints or []),
        weight=weight,
        ttl_seconds_after_empty=ttl_seconds_after_empty,
        ttl_seconds_until_expired=ttl_seconds_until_expired,
    )
    if limits is not None:
        spec.limits = Limits(resources=parse_resource_list(limits))
    if consolidation_enabled is not None:
        spec.consolidation = Consolidation(enabled=consolidation_enabled)
    if spec.provider is None and spec.provider_ref is None:
        spec.provider = {"fake": True}  # reference test.Provisioner defaults one
    p = Provisioner(metadata=ObjectMeta(name=name or unique_name("provisioner")), spec=spec)
    p.metadata.namespace = ""
    return p


def make_node(
    name: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    capacity: Optional[Dict[str, object]] = None,
    allocatable: Optional[Dict[str, object]] = None,
    taints: Optional[List[Taint]] = None,
    provider_id: str = "",
    ready: bool = True,
) -> Node:
    node = Node(metadata=ObjectMeta(name=name or unique_name("node"), labels=dict(labels or {})))
    node.metadata.namespace = ""
    node.spec.taints = list(taints or [])
    node.spec.provider_id = provider_id or f"fake:///{node.metadata.name}"
    node.status.capacity = parse_resource_list(capacity or {})
    node.status.allocatable = parse_resource_list(allocatable or capacity or {})
    node.status.conditions.append(
        Condition(type="Ready", status="True" if ready else "False")
    )
    return node
