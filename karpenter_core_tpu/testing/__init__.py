"""Test object builders — the analog of reference pkg/test (pods.go etc.)."""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from karpenter_core_tpu.api.provisioner import (
    Consolidation,
    Limits,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_core_tpu.kube.objects import (
    Affinity,
    Condition,
    Container,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.utils.resources import parse_resource_list

_counter = itertools.count(1)


class FakeClock:
    """Steppable clock (the analog of clock/testing.FakeClock the reference
    threads through every TTL-sensitive controller).

    `sleep` gives TTL waits real semantics under test: it blocks while
    another thread drives the clock forward with `advance` (so the 15s
    revalidation window is genuinely exercised, validation.go:60-67), but
    if the clock sits still for `grace` real seconds — no stepper thread —
    it jumps itself to the deadline instead of deadlocking the test."""

    def __init__(self, t: float = 1_700_000_000.0, grace: float = 0.05):
        import threading

        self._t = t
        self._grace = grace
        self._cond = threading.Condition()

    @property
    def t(self) -> float:
        return self._t

    def __call__(self) -> float:
        with self._cond:
            return self._t

    def advance(self, seconds: float) -> None:
        with self._cond:
            self._t += seconds
            self._cond.notify_all()

    def sleep(self, seconds: float) -> None:
        with self._cond:
            deadline = self._t + seconds
            while self._t < deadline:
                last = self._t
                self._cond.wait(timeout=self._grace)
                if self._t == last:  # nobody is stepping: jump
                    self._t = deadline
                    self._cond.notify_all()


def unique_name(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_counter)}"


def make_pod(
    name: Optional[str] = None,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    requests: Optional[Dict[str, object]] = None,
    limits: Optional[Dict[str, object]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    node_name: str = "",
    tolerations: Optional[List[Toleration]] = None,
    topology_spread: Optional[List[TopologySpreadConstraint]] = None,
    pod_affinity_required: Optional[List[PodAffinityTerm]] = None,
    pod_affinity_preferred: Optional[List[WeightedPodAffinityTerm]] = None,
    pod_anti_affinity_required: Optional[List[PodAffinityTerm]] = None,
    pod_anti_affinity_preferred: Optional[List[WeightedPodAffinityTerm]] = None,
    node_affinity_required: Optional[List[NodeSelectorTerm]] = None,
    node_affinity_preferred=None,
    host_ports: Optional[List[int]] = None,
    owner_kind: str = "",
    phase: str = "Pending",
    unschedulable: bool = True,
    init_requests: Optional[Dict[str, object]] = None,
    init_limits: Optional[Dict[str, object]] = None,
) -> Pod:
    """A pending, unschedulable pod by default (marked with the PodScheduled
    Unschedulable condition like GetPendingPods expects).

    Requests default from limits per-resource, matching the apiserver's
    admission defaulting the reference's envtest pods get for free (its
    suites routinely set only Limits)."""

    def _requests(reqs, lims):
        out = dict(parse_resource_list(lims or {}))
        out.update(parse_resource_list(reqs or {}))
        return out

    containers = [
        Container(
            resources=ResourceRequirements(
                requests=_requests(requests, limits),
                limits=parse_resource_list(limits or {}),
            ),
            ports=[ContainerPort(host_port=p) for p in (host_ports or [])],
        )
    ]
    init_containers = []
    if init_requests or init_limits:
        init_containers = [
            Container(
                resources=ResourceRequirements(
                    requests=_requests(init_requests, init_limits),
                    limits=parse_resource_list(init_limits or {}),
                )
            )
        ]
    affinity = None
    if any(
        [
            pod_affinity_required,
            pod_affinity_preferred,
            pod_anti_affinity_required,
            pod_anti_affinity_preferred,
            node_affinity_required,
            node_affinity_preferred,
        ]
    ):
        affinity = Affinity(
            node_affinity=NodeAffinity(
                required=list(node_affinity_required or []),
                preferred=list(node_affinity_preferred or []),
            )
            if (node_affinity_required or node_affinity_preferred)
            else None,
            pod_affinity=PodAffinity(
                required=list(pod_affinity_required or []),
                preferred=list(pod_affinity_preferred or []),
            )
            if (pod_affinity_required or pod_affinity_preferred)
            else None,
            pod_anti_affinity=PodAntiAffinity(
                required=list(pod_anti_affinity_required or []),
                preferred=list(pod_anti_affinity_preferred or []),
            )
            if (pod_anti_affinity_required or pod_anti_affinity_preferred)
            else None,
        )
    pod = Pod(
        metadata=ObjectMeta(
            name=name or unique_name("pod"),
            namespace=namespace,
            labels=dict(labels or {}),
            annotations=dict(annotations or {}),
        ),
        spec=PodSpec(
            node_name=node_name,
            node_selector=dict(node_selector or {}),
            affinity=affinity,
            tolerations=list(tolerations or []),
            containers=containers,
            init_containers=init_containers,
            topology_spread_constraints=list(topology_spread or []),
        ),
    )
    pod.status.phase = phase
    if unschedulable and not node_name:
        pod.status.conditions.append(
            Condition(type="PodScheduled", status="False", reason="Unschedulable")
        )
    if owner_kind:
        pod.metadata.owner_references.append(OwnerReference(kind=owner_kind, name="owner"))
    return pod


def make_provisioner(
    name: Optional[str] = None,
    requirements: Optional[List[NodeSelectorRequirement]] = None,
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    startup_taints: Optional[List[Taint]] = None,
    limits: Optional[Dict[str, object]] = None,
    weight: Optional[int] = None,
    ttl_seconds_after_empty: Optional[int] = None,
    ttl_seconds_until_expired: Optional[int] = None,
    consolidation_enabled: Optional[bool] = None,
) -> Provisioner:
    spec = ProvisionerSpec(
        requirements=list(requirements or []),
        labels=dict(labels or {}),
        annotations=dict(annotations or {}),
        taints=list(taints or []),
        startup_taints=list(startup_taints or []),
        weight=weight,
        ttl_seconds_after_empty=ttl_seconds_after_empty,
        ttl_seconds_until_expired=ttl_seconds_until_expired,
    )
    if limits is not None:
        spec.limits = Limits(resources=parse_resource_list(limits))
    if consolidation_enabled is not None:
        spec.consolidation = Consolidation(enabled=consolidation_enabled)
    if spec.provider is None and spec.provider_ref is None:
        spec.provider = {"fake": True}  # reference test.Provisioner defaults one
    p = Provisioner(metadata=ObjectMeta(name=name or unique_name("provisioner")), spec=spec)
    p.metadata.namespace = ""
    return p


def make_pool_provisioners(pools: int, universe) -> tuple:
    """`pools` selector-scoped provisioners ("pool-<p>" requiring
    `team In [pool-<p>]`) over one shared instance-type universe — the
    canonical PARTITIONABLE control-plane shape for the segmented pack
    scan (ISSUE 14): each pool's pods and nodes are invisible to every
    other pool's, so the conflict partition splits along pools. Shared by
    the segmented parity/tripwire suites, bench's segmented A/B, and
    `hack/segment_smoke.py`; pod construction stays with the caller
    (pods select a pool with `node_selector={"team": "pool-<p>"}`).
    Returns (provisioners, instance_types_by_provisioner)."""
    provisioners, its = [], {}
    for p in range(pools):
        pool = f"pool-{p}"
        provisioners.append(make_provisioner(
            name=pool,
            requirements=[NodeSelectorRequirement(
                key="team", operator="In", values=[pool]
            )],
        ))
        its[pool] = universe
    return provisioners, its


def make_machine(
    name: Optional[str] = None,
    provider_id: str = "",
    labels: Optional[Dict[str, str]] = None,
    requirements: Optional[List[NodeSelectorRequirement]] = None,
    capacity: Optional[Dict[str, object]] = None,
    allocatable: Optional[Dict[str, object]] = None,
    launched: bool = False,
    registered: bool = False,
    initialized: bool = False,
):
    """test.Machine analog (reference pkg/test/machines.go): a launch-intent
    record with optional lifecycle conditions pre-set."""
    from karpenter_core_tpu.api.machine import (
        CONDITION_MACHINE_INITIALIZED,
        CONDITION_MACHINE_LAUNCHED,
        CONDITION_MACHINE_REGISTERED,
        Machine,
        MachineSpec,
        MachineStatus,
    )

    machine = Machine(
        metadata=ObjectMeta(name=name or unique_name("machine"),
                            labels=dict(labels or {})),
        spec=MachineSpec(requirements=list(requirements or [])),
        status=MachineStatus(
            provider_id=provider_id,
            capacity=parse_resource_list(capacity or {}),
            allocatable=parse_resource_list(
                (capacity if allocatable is None else allocatable) or {}
            ),
        ),
    )
    if launched:
        machine.set_condition(CONDITION_MACHINE_LAUNCHED, "True")
    if registered:
        machine.set_condition(CONDITION_MACHINE_REGISTERED, "True")
    if initialized:
        machine.set_condition(CONDITION_MACHINE_INITIALIZED, "True")
    return machine


def make_daemonset(
    name: Optional[str] = None,
    namespace: str = "default",
    requests: Optional[Dict[str, object]] = None,
    limits: Optional[Dict[str, object]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    tolerations: Optional[List[Toleration]] = None,
    node_affinity_required: Optional[List[NodeSelectorTerm]] = None,
    init_requests: Optional[Dict[str, object]] = None,
    init_limits: Optional[Dict[str, object]] = None,
) -> "DaemonSet":
    """test.DaemonSet analog: carries the pod template the scheduler uses for
    per-template daemon overhead (reference pkg/test/daemonsets.go)."""
    from karpenter_core_tpu.kube.objects import DaemonSet

    # the template IS a pod spec: compose through make_pod (the reference's
    # test.DaemonSet(PodOptions) shape) so the two builders cannot drift
    template = make_pod(
        requests=requests,
        limits=limits,
        node_selector=node_selector,
        tolerations=tolerations,
        node_affinity_required=node_affinity_required,
        init_requests=init_requests,
        init_limits=init_limits,
        unschedulable=False,
    ).spec
    return DaemonSet(
        metadata=ObjectMeta(name=name or unique_name("ds"), namespace=namespace),
        pod_template_spec=template,
    )


def make_storage_class(name: str, provisioner: str = "", zones: Optional[List[str]] = None):
    """test.StorageClass analog (pkg/test/storage.go)."""
    from karpenter_core_tpu.kube.objects import (
        LABEL_TOPOLOGY_ZONE,
        StorageClass,
        TopologySelectorLabelRequirement,
        TopologySelectorTerm,
    )

    sc = StorageClass(metadata=ObjectMeta(name=name), provisioner=provisioner)
    if zones:
        sc.allowed_topologies = [
            TopologySelectorTerm(
                match_label_expressions=[
                    TopologySelectorLabelRequirement(
                        key=LABEL_TOPOLOGY_ZONE, values=list(zones)
                    )
                ]
            )
        ]
    return sc


def make_pvc(name: str, namespace: str = "default", storage_class: Optional[str] = None,
             volume_name: str = ""):
    """test.PersistentVolumeClaim analog."""
    from karpenter_core_tpu.kube.objects import (
        PersistentVolumeClaim,
        PersistentVolumeClaimSpec,
    )

    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=PersistentVolumeClaimSpec(
            storage_class_name=storage_class, volume_name=volume_name
        ),
    )


def make_pv(name: str, driver: str = "", zones: Optional[List[str]] = None,
            storage_class: str = ""):
    """test.PersistentVolume analog; driver='' models non-CSI (e.g. NFS)."""
    from karpenter_core_tpu.kube.objects import (
        CSIPersistentVolumeSource,
        LABEL_TOPOLOGY_ZONE,
        PersistentVolume,
        PersistentVolumeSpec,
    )

    spec = PersistentVolumeSpec(storage_class_name=storage_class)
    if driver:
        spec.csi = CSIPersistentVolumeSource(driver=driver)
    if zones:
        spec.node_affinity_required = [
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(LABEL_TOPOLOGY_ZONE, "In", list(zones))
                ]
            )
        ]
    return PersistentVolume(metadata=ObjectMeta(name=name), spec=spec)


def make_csinode(node_name: str, driver: str, allocatable: Optional[int] = None):
    """storagev1.CSINode analog carrying per-driver attach limits."""
    from karpenter_core_tpu.kube.objects import CSINode, CSINodeDriver

    return CSINode(
        metadata=ObjectMeta(name=node_name),
        drivers=[CSINodeDriver(name=driver, allocatable_count=allocatable)],
    )


def pvc_volume(claim_name: str):
    from karpenter_core_tpu.kube.objects import (
        PersistentVolumeClaimVolumeSource,
        Volume,
    )

    return Volume(
        name=claim_name,
        persistent_volume_claim=PersistentVolumeClaimVolumeSource(claim_name=claim_name),
    )


def make_node(
    name: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    capacity: Optional[Dict[str, object]] = None,
    allocatable: Optional[Dict[str, object]] = None,
    taints: Optional[List[Taint]] = None,
    provider_id: str = "",
    ready: bool = True,
) -> Node:
    node = Node(metadata=ObjectMeta(name=name or unique_name("node"), labels=dict(labels or {})))
    node.metadata.namespace = ""
    node.spec.taints = list(taints or [])
    node.spec.provider_id = provider_id or f"fake:///{node.metadata.name}"
    node.status.capacity = parse_resource_list(capacity or {})
    node.status.allocatable = parse_resource_list(allocatable or capacity or {})
    node.status.conditions.append(
        Condition(type="Ready", status="True" if ready else "False")
    )
    return node


def solve_scan_parity(solvers, pods, provisioners, instance_types,
                      nodes=None, kube_client=None, max_nodes=96):
    """Solve the same workload through the sequential AND segmented pack
    scans and assert the placements are flightrec-canonical BYTE-IDENTICAL
    — the ISSUE 14 parity bar, shared by test_segmented,
    test_screen_parity and both differential-fuzz suites so the bar can
    only be raised in one place. `solvers` is the caller's cache dict (one
    TPUSolver per mode, so each suite compiles once per geometry family);
    segment stats are read off solvers["segmented"].last_segment_stats.
    Returns (sequential_result, segmented_result)."""
    import copy

    from karpenter_core_tpu.obs import flightrec
    from karpenter_core_tpu.obs.flightrec import (
        canonical_placements,
        placements_json,
    )
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    results = {}
    for mode in ("sequential", "segmented"):
        solver = solvers.setdefault(
            mode, TPUSolver(max_nodes=max_nodes, pack_scan=mode)
        )
        results[mode] = solver.solve(
            copy.deepcopy(pods), provisioners, instance_types,
            state_nodes=[n.deep_copy() for n in nodes] if nodes else None,
            kube_client=kube_client,
        )
    seq, seg = results["sequential"], results["segmented"]
    a = placements_json(canonical_placements(seq))
    b = placements_json(canonical_placements(seg))
    if a != b:
        diff = flightrec.diff_placements(
            canonical_placements(seq), canonical_placements(seg)
        )
        raise AssertionError(
            "segmented diverged from sequential:\n" + "\n".join(diff)
        )
    assert seg.rounds == seq.rounds
    assert len(seg.failed_pods) == len(seq.failed_pods)
    return seq, seg
