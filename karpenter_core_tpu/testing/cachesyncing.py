"""CacheSyncingClient — writes block until the watch fan-out observes them.

The analog of reference pkg/test/cachesyncingclient.go:45: envtest suites
wrap the client so a test that writes an object and immediately asserts on
informer-driven state can't flake on watch latency. Here the wrapper holds
its own watch queues and, after every write, drains them until the event
for that object (same kind, key, and resource version) has been delivered —
proving the client's notification fan-out handed the event to every
subscriber queue registered before the write."""
from __future__ import annotations

import queue
import time
from typing import Dict

from karpenter_core_tpu.kube.objects import object_key


class CacheSyncingClient:
    """Wraps a kube client; create/update/delete block until self-observed."""

    def __init__(self, inner, timeout: float = 5.0):
        self._inner = inner
        self._timeout = timeout
        self._queues: Dict[str, "queue.Queue"] = {}

    def __getattr__(self, name):  # read paths pass straight through
        return getattr(self._inner, name)

    def _queue_for(self, kind: str) -> "queue.Queue":
        q = self._queues.get(kind)
        if q is None:
            q = self._inner.watch(kind, backlog=False)
            self._queues[kind] = q
        return q

    def _await_event(self, kind: str, key, min_rv: int, deleted: bool = False):
        q = self._queue_for(kind)
        deadline = time.monotonic() + self._timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"watch never observed {'deletion of ' if deleted else ''}"
                    f"{kind} {key} (rv>={min_rv}) within {self._timeout}s"
                )
            try:
                event, obj = q.get(timeout=remaining)
            except queue.Empty:
                continue
            if object_key(obj) != key:
                continue
            if deleted and event == "DELETED":
                return
            if not deleted and obj.metadata.resource_version >= min_rv:
                return

    def create(self, obj):
        kind = type(obj).__name__
        self._queue_for(kind)  # subscribe BEFORE the write
        created = self._inner.create(obj)
        self._await_event(kind, object_key(created), created.metadata.resource_version)
        return created

    def update(self, obj):
        kind = type(obj).__name__
        self._queue_for(kind)
        updated = self._inner.update(obj)
        self._await_event(kind, object_key(updated), updated.metadata.resource_version)
        return updated

    def apply(self, obj):
        kind = type(obj).__name__
        self._queue_for(kind)
        applied = self._inner.apply(obj)
        self._await_event(kind, object_key(applied), applied.metadata.resource_version)
        return applied

    def update_status(self, obj):
        kind = type(obj).__name__
        self._queue_for(kind)
        updated = self._inner.update_status(obj)
        self._await_event(kind, object_key(updated), updated.metadata.resource_version)
        return updated

    def delete(self, obj_or_kind, namespace: str = None, name: str = None):
        if isinstance(obj_or_kind, str):
            kind, ns, nm = obj_or_kind, namespace or "", name
        else:
            kind = type(obj_or_kind).__name__
            ns = getattr(obj_or_kind.metadata, "namespace", "")
            nm = obj_or_kind.metadata.name
        self._queue_for(kind)
        from karpenter_core_tpu.kube.objects import NamespacedName

        self._inner.delete(obj_or_kind, namespace, name)
        self._await_event(kind, NamespacedName(ns, nm), 0, deleted=True)
