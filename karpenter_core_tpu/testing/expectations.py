"""Expectation harness — the analog of reference
pkg/test/expectations/expectations.go (29 Expect* helpers) over the
in-memory control plane.

The reference's envtest suites lean on this layer to stay cheap to write:
`ExpectProvisioned` runs a full schedule+launch+bind cycle in one line and
`ExpectSkew` turns topology assertions into dict comparisons
(expectations.go:216-257, 336-361). `Env` plays the role of the suite-level
environment (pkg/test/environment.go:69-118): a wired operator over the
in-memory client with a fake cloud provider and steppable clock.

Helpers raise AssertionError with the same diagnostic shape the reference's
Gomega matchers produce, so ported specs read 1:1.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_core_tpu.api.settings import Settings
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.objects import (
    LABEL_HOSTNAME,
    Node,
    Pod,
)
from karpenter_core_tpu.operator import new_operator
from karpenter_core_tpu.testing import FakeClock
from karpenter_core_tpu.utils import podutils


class Env:
    """Suite environment (environment.go:69-118 analog): operator + fake
    cloud provider + fake clock, exposing the Expect* helpers as methods.

    solver=None uses the host greedy solver (bit-true to the reference's
    serial FFD); pass a TPUSolver to run the same specs through the device
    path.
    """

    def __init__(
        self,
        universe=None,
        settings: Optional[Settings] = None,
        solver=None,
        with_webhooks: bool = False,
    ):
        self.clock = FakeClock()
        self.universe = universe if universe is not None else fake.default_universe()
        self.cloud_provider = fake.FakeCloudProvider(self.universe)
        self.op = new_operator(
            self.cloud_provider,
            settings=settings or Settings(),
            solver=solver,
            clock=self.clock,
            with_webhooks=with_webhooks,
        )

    # conveniences mirroring the suite-level globals (env.Client, cluster, ...)
    @property
    def kube(self):
        return self.op.kube_client

    @property
    def cluster(self):
        return self.op.cluster

    @property
    def provisioning(self):
        return self.op.provisioning

    # -- object lifecycle (expectations.go:58-213) -------------------------

    def expect_applied(self, *objects):
        """Create-or-update each object INCLUDING its status
        (ExpectApplied, expectations.go:110-143: the Go helper follows the
        spec write with a Status().Update so suites that seed status through
        it keep working). A plain update() alone would silently drop status
        changes on subresource kinds (kube/client.py
        STATUS_SUBRESOURCE_KINDS)."""
        for obj in objects:
            kind = type(obj).__name__
            current = self.kube.get(
                kind, getattr(obj.metadata, "namespace", ""), obj.metadata.name
            )
            if current is None:
                self.kube.create(obj)
            else:
                obj.metadata.resource_version = current.metadata.resource_version
                self.kube.update(obj)
                if hasattr(obj, "status"):
                    self.kube.update_status(obj)
        return objects[0] if len(objects) == 1 else objects

    def expect_exists(self, obj_or_kind, name: str = None, namespace: str = ""):
        """ExpectExists (expectations.go:58-66)."""
        if name is None:
            kind = type(obj_or_kind).__name__
            namespace = getattr(obj_or_kind.metadata, "namespace", "")
            name = obj_or_kind.metadata.name
        else:
            kind = obj_or_kind
        got = self.kube.get(kind, namespace, name)
        assert got is not None, f"expected {kind} {namespace}/{name} to exist"
        return got

    def expect_not_found(self, *objects):
        """ExpectNotFound (expectations.go:84-96)."""
        for obj in objects:
            kind = type(obj).__name__
            ns = getattr(obj.metadata, "namespace", "")
            got = self.kube.get(kind, ns, obj.metadata.name)
            assert got is None, (
                f"expected {kind} {ns}/{obj.metadata.name} to be deleted, "
                f"but it still exists"
            )

    def expect_deleted(self, *objects):
        """Delete + assert gone (ExpectDeleted, expectations.go:145-152).
        Runs finalization so finalizer-carrying objects actually go away.

        Deleting a Node also deletes its 1:1 Machine record: the reference's
        Launch persists no Machine CR (provisioner.go:304-361), so a suite
        spec that deletes a node expects ALL its capacity gone — here the
        paired Machine is the termination controller's job, which these
        specs don't drive."""
        for obj in objects:
            kind = type(obj).__name__
            obj.metadata.finalizers = []
            try:
                self.kube.update(obj)
            except Exception:
                pass
            self.kube.delete(kind, getattr(obj.metadata, "namespace", ""), obj.metadata.name)
            if kind == "Node":
                machine = self.kube.get("Machine", "", obj.metadata.name)
                if machine is not None:
                    machine.metadata.finalizers = []
                    self.kube.update(machine)
                    self.kube.delete("Machine", "", machine.metadata.name)
        self.expect_not_found(*objects)

    def expect_finalizers_removed(self, *objects):
        """ExpectFinalizersRemoved (expectations.go:203-213)."""
        for obj in objects:
            kind = type(obj).__name__
            live = self.kube.get(kind, getattr(obj.metadata, "namespace", ""), obj.metadata.name)
            if live is not None:
                live.metadata.finalizers = []
                self.kube.update(live)

    # -- scheduling cycle (expectations.go:216-257) ------------------------

    def expect_provisioned(self, *pods: Pod) -> Dict[str, Optional[Node]]:
        """Apply the pods, run one full schedule+launch cycle, and BIND the
        scheduled pods to their nodes (ExpectProvisioned,
        expectations.go:216-227). Returns {pod name: Node or None}."""
        bindings = self.expect_provisioned_no_binding(*pods)
        for pod in pods:
            node = bindings.get(pod.metadata.name)
            if node is not None:
                self.expect_manual_binding(pod, node)
        return bindings

    def expect_provisioned_no_binding(self, *pods: Pod) -> Dict[str, Optional[Node]]:
        """ExpectProvisionedNoBinding (expectations.go:233-257): schedule +
        launch, no binding."""
        self.expect_applied(*pods)
        self.op.sync_state()
        result = self.provisioning.schedule()
        bindings: Dict[str, Optional[Node]] = {p.metadata.name: None for p in pods}
        if result is None:
            return bindings
        names = self.provisioning.launch_machines(result.new_machines)
        for machine, node_name in zip(result.new_machines, names):
            if not node_name:
                continue
            node = self.kube.get("Node", "", node_name)
            for pod in machine.pods:
                bindings[pod.metadata.name] = node
        for state_node, assigned in result.existing_assignments:
            node = state_node.node
            if node is None and state_node.machine is not None:
                node = self.kube.get("Node", "", state_node.name())
            for pod in assigned:
                bindings[pod.metadata.name] = node
        return bindings

    def expect_scheduled(self, pod: Pod) -> Node:
        """ExpectScheduled (expectations.go:98-102): the live pod is bound;
        returns its node."""
        live = self.expect_exists(pod)
        assert live.spec.node_name, (
            f"expected {live.metadata.namespace}/{live.metadata.name} to be scheduled"
        )
        return self.expect_exists("Node", live.spec.node_name)

    def expect_not_scheduled(self, pod: Pod) -> Pod:
        """ExpectNotScheduled (expectations.go:104-108)."""
        live = self.expect_exists(pod)
        assert not live.spec.node_name, (
            f"expected {live.metadata.namespace}/{live.metadata.name} "
            f"to not be scheduled (bound to {live.spec.node_name})"
        )
        return live

    def expect_manual_binding(self, pod: Pod, node: Node):
        """Bind pod->node and track it in cluster state (ExpectManualBinding,
        expectations.go:314-334 + the cluster.UpdatePod call in
        ExpectProvisioned)."""
        live = self.kube.get("Pod", pod.metadata.namespace, pod.metadata.name) or pod
        live.spec.node_name = node.metadata.name
        # a bound pod is no longer "unschedulable pending"
        live.status.conditions = [
            c for c in live.status.conditions if c.type != "PodScheduled"
        ]
        try:
            self.kube.update(live)
        except Exception:
            self.kube.create(live)
        pod.spec.node_name = node.metadata.name
        self.cluster.update_pod(live)

    # -- controller drives -------------------------------------------------

    def expect_reconcile_succeeded(self, reconciler, obj):
        """ExpectReconcileSucceeded (expectations.go:260-264)."""
        try:
            return reconciler.reconcile(obj)
        except Exception as exc:  # pragma: no cover - assertion path
            raise AssertionError(
                f"expected reconcile of {type(obj).__name__} "
                f"{obj.metadata.name} to succeed: {exc}"
            ) from exc

    def expect_reconcile_failed(self, reconciler, obj):
        """ExpectReconcileFailed (expectations.go:266-269)."""
        try:
            reconciler.reconcile(obj)
        except Exception:
            return
        raise AssertionError(
            f"expected reconcile of {type(obj).__name__} {obj.metadata.name} to fail"
        )

    # -- topology (expectations.go:336-361) --------------------------------

    def expect_skew(self, namespace: str, constraint) -> Dict[str, int]:
        """Pods-per-domain for a spread constraint over the LIVE cluster
        (ExpectSkew): counts bound, non-terminal pods matching the
        constraint's selector, keyed by the node's domain (node name for
        hostname)."""
        nodes = {n.metadata.name: n for n in self.kube.list("Node")}
        skew: Dict[str, int] = {}
        for pod in self.kube.list("Pod"):
            if namespace and pod.metadata.namespace != namespace:
                continue
            if podutils.is_terminal(pod):
                continue
            if constraint.label_selector is not None and not (
                constraint.label_selector.matches(pod.metadata.labels)
            ):
                continue
            node = nodes.get(pod.spec.node_name)
            if node is None:
                continue
            if constraint.topology_key == LABEL_HOSTNAME:
                skew[node.metadata.name] = skew.get(node.metadata.name, 0) + 1
            else:
                domain = node.metadata.labels.get(constraint.topology_key)
                if domain is not None:
                    skew[domain] = skew.get(domain, 0) + 1
        return skew

    # -- misc --------------------------------------------------------------

    @staticmethod
    def expect_resources(expected: dict, real: dict):
        """ExpectResources (expectations.go:363-371): every expected
        resource present with the same value."""
        for key, value in expected.items():
            assert key in real, f"expected resource {key} missing (have {sorted(real)})"
            assert abs(real[key] - float(value)) < 1e-9, (
                f"resource {key}: expected {value}, got {real[key]}"
            )

    def expect_status_condition(self, obj, cond_type: str):
        """ExpectStatusConditionExists (expectations.go:271-278)."""
        for cond in obj.status.conditions:
            if cond.type == cond_type:
                return cond
        raise AssertionError(
            f"expected condition {cond_type} on {obj.metadata.name} "
            f"(have {[c.type for c in obj.status.conditions]})"
        )

    def expect_owner_reference(self, obj, owner):
        """ExpectOwnerReferenceExists (expectations.go:280-287)."""
        for ref in obj.metadata.owner_references:
            if ref.kind == type(owner).__name__ and ref.name == owner.metadata.name:
                return ref
        raise AssertionError(
            f"expected {obj.metadata.name} to be owned by {owner.metadata.name}"
        )

    def expect_cleaned_up(self):
        """Wipe every object (ExpectCleanedUp, expectations.go:174-201)."""
        for kind in ("Pod", "Node", "Machine", "Provisioner", "PersistentVolumeClaim",
                     "PersistentVolume", "DaemonSet", "PodDisruptionBudget"):
            for obj in self.kube.list(kind):
                obj.metadata.finalizers = []
                try:
                    self.kube.update(obj)
                except Exception:
                    pass
                try:
                    self.kube.delete(kind, getattr(obj.metadata, "namespace", ""),
                                     obj.metadata.name)
                except Exception:
                    pass

    def drop_machine(self, node: Node):
        """Delete the 1:1 Machine record behind a launched node, leaving a
        raw Node. Reference suite specs that mutate node taints/labels
        directly model the machine-less path (its Launch persists no Machine
        CR, provisioner.go:304-361): with a Machine present, pre-init taints
        come from machine.spec (node.go:148-176) and the mutation would be
        invisible — which is correct machine-linked behavior, but not what
        those specs exercise."""
        machine = self.kube.get("Machine", "", node.metadata.name)
        if machine is not None:
            machine.metadata.finalizers = []
            self.kube.update(machine)
            self.kube.delete("Machine", "", machine.metadata.name)
        self.op.sync_state()

    def bound_pods(self, node: Node) -> List[Pod]:
        return [
            p for p in self.kube.list("Pod")
            if p.spec.node_name == node.metadata.name
        ]
