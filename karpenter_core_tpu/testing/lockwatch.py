"""Lock-order-graph recorder: the runtime half of the concurrency lints.

The static `guarded-by` rule catches unguarded writes; what it cannot see
is ACQUISITION ORDER. With 28 lock sites across operator/solver/obs, two
code paths taking the same pair of locks in opposite order is a deadlock
that only fires under the right interleaving — the Go reference gets this
from the `-race`-instrumented presubmit; this module is the Python analog.

Mechanism: `install()` monkeypatches `threading.Lock` / `threading.RLock`
with factories that return wrapping proxies for locks ALLOCATED FROM
PACKAGE CODE (the allocation frame decides — jax/stdlib/test locks pass
through untouched, so library internals like `queue.Queue` and
`threading.Condition`'s internal RLock keep their exact native types and
the suite pays no broad overhead). Each proxy records, per thread, the
stack of held lock SITES (allocation file:line — instances pool by site so
per-object locks aggregate); acquiring B while holding A adds the edge
A->B with a witness. A cycle in the site graph = an acquisition-order
inversion = a potential deadlock, reported with both witnesses.

Arming: tests/conftest.py installs the global watcher unless
KARPENTER_LOCKWATCH is falsy, and fails the session on cycles at exit.
Standalone `LockWatch` instances (tests, tools) can `make_lock()` tracked
locks without touching the global patch.

Reentrant acquisition of the same lock object never adds an edge, and
self-edges at one site (two instances from the same allocation line) are
ignored: per-instance sibling locks (one lock per watch subscription, per
solver, ...) are routinely held pairwise in either order without a global
ordering contract, and flagging them would drown the real inversions.
"""
from __future__ import annotations

import itertools
import os
import sys
import threading
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

# the one lock guarding the watcher's own state must never be a proxy:
# allocate the raw C primitive directly
_allocate_lock = threading._allocate_lock

# stable per-lock identity: id() recycles after GC, so locksets keyed by
# id() could alias a dead lock with a fresh one — a monotonic uid cannot
_uid_counter = itertools.count(1)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF = os.path.abspath(__file__)


def _default_filter(filename: str) -> bool:
    """Track locks allocated from package source only (not this module)."""
    f = os.path.abspath(filename)
    return f.startswith(_PKG_DIR + os.sep) and f != _SELF


class _Acquisition:
    __slots__ = ("site", "count", "uids")

    def __init__(self, site: str, uid: int) -> None:
        self.site = site
        self.count = 1
        # uids of the lock INSTANCES held under this site entry (same-site
        # siblings pool into one entry; racewatch locksets need identity)
        self.uids = [uid]


class TrackedLock:
    """Proxy over a real lock primitive, recording ordering edges."""

    def __init__(self, watch: "LockWatch", inner, site: str) -> None:
        self._watch = watch
        self._inner = inner
        self._site = site
        self._uid = next(_uid_counter)

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._watch._note_acquire(self)
        return got

    __enter__ = acquire

    def release(self) -> None:
        self._inner.release()
        self._watch._note_release(self)

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition() support when constructed around a tracked RLock
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        self._watch._note_release(self, full=True)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        self._watch._note_acquire(self)

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<TrackedLock site={self._site} inner={self._inner!r}>"


class LockWatch:
    """Acquisition-order graph over lock allocation sites."""

    def __init__(self, track_filter=None) -> None:
        self._mu = _allocate_lock()
        self._filter = track_filter or _default_filter
        self._local = threading.local()
        # site -> site -> witness string
        self._edges: Dict[str, Dict[str, str]] = {}
        self._sites: Set[str] = set()
        self._installed = False
        self._orig_lock = None
        self._orig_rlock = None
        # uid -> allocation site (racewatch reports name locks by site)
        self._uid_sites: Dict[int, str] = {}
        # uids released on a thread that never acquired them: cross-thread
        # HANDOFF (semaphore-style) usage. The acquiring thread's stack
        # still carries the entry and would leak it forever, poisoning
        # ordering edges and racewatch locksets — tainted uids are purged
        # from every thread's held stack lazily and never trusted again
        # (code that hands a lock between threads should use a Semaphore)
        self._tainted_uids: Set[int] = set()
        # allocation observers: fn(lock, frame_or_None) called for every
        # tracked allocation — racewatch hooks here to discover the OWNING
        # instance (the `self` in the allocating frame) without lockwatch
        # knowing anything about attribute instrumentation
        self._alloc_hooks: List[Callable] = []

    # -- allocation --------------------------------------------------------

    def make_lock(self, site: Optional[str] = None, rlock: bool = False):
        """Explicitly allocate a tracked lock (tests/tools)."""
        inner = (self._orig_rlock or threading.RLock)() if rlock else (
            (self._orig_lock or threading.Lock)()
        )
        # unwrap accidental double-tracking when the global patch is live
        if isinstance(inner, TrackedLock):
            inner = inner._inner
        site = site or self._caller_site(depth=2)
        lock = TrackedLock(self, inner, site)
        with self._mu:
            self._sites.add(site)
            self._uid_sites[lock._uid] = site
        self._run_alloc_hooks(lock, sys._getframe(1))
        return lock

    @staticmethod
    def _caller_site(depth: int) -> str:
        frame = sys._getframe(depth)
        return f"{frame.f_code.co_filename}:{frame.f_lineno}"

    def _factory(self, orig, kind: str):
        watch = self

        def allocate():
            inner = orig()
            frame = sys._getframe(1)
            if not watch._filter(frame.f_code.co_filename):
                return inner
            rel = os.path.relpath(frame.f_code.co_filename, os.path.dirname(_PKG_DIR))
            site = f"{rel}:{frame.f_lineno}"
            lock = TrackedLock(watch, inner, site)
            with watch._mu:
                watch._sites.add(site)
                watch._uid_sites[lock._uid] = site
            watch._run_alloc_hooks(lock, frame)
            return lock

        allocate.__name__ = kind
        return allocate

    def add_allocation_hook(self, hook: Callable) -> None:
        """Register fn(lock, frame) to observe every tracked allocation.
        `frame` is the allocating package frame (None for explicit
        make_lock sites with no meaningful caller). Hooks run OUTSIDE the
        watcher's lock and must not allocate tracked locks themselves."""
        with self._mu:
            self._alloc_hooks.append(hook)

    def _run_alloc_hooks(self, lock: "TrackedLock", frame) -> None:
        with self._mu:
            hooks = list(self._alloc_hooks)
        for hook in hooks:
            hook(lock, frame)

    def install(self) -> "LockWatch":
        """Patch threading.Lock/RLock so package allocations are tracked.
        Idempotent; returns self."""
        with self._mu:
            if self._installed:
                return self
            self._orig_lock = threading.Lock
            self._orig_rlock = threading.RLock
            self._installed = True
        threading.Lock = self._factory(self._orig_lock, "Lock")
        threading.RLock = self._factory(self._orig_rlock, "RLock")
        return self

    def uninstall(self) -> None:
        with self._mu:
            if not self._installed:
                return
            self._installed = False
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock

    # -- recording ---------------------------------------------------------

    def _held(self) -> List[_Acquisition]:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        if held and self._tainted_uids:
            with self._mu:
                tainted = set(self._tainted_uids)
            kept = []
            for acq in held:
                live = [u for u in acq.uids if u not in tainted]
                if not live:
                    continue  # the leaked handoff entry: drop it
                acq.uids = live
                kept.append(acq)
            if len(kept) != len(held):
                held[:] = kept
        return held

    def held_sites(self) -> List[str]:
        """Allocation sites of the locks the CURRENT thread holds, outer
        to inner."""
        return [acq.site for acq in self._held()]

    def held_lock_uids(self) -> FrozenSet[int]:
        """Uids of the lock instances the CURRENT thread holds — the
        lockset racewatch intersects per access."""
        out: Set[int] = set()
        for acq in self._held():
            out.update(acq.uids)
        return frozenset(out)

    def site_of_uid(self, uid: int) -> str:
        with self._mu:
            return self._uid_sites.get(uid, f"uid-{uid}")

    def _note_acquire(self, lock: TrackedLock) -> None:
        held = self._held()
        for acq in held:
            if acq.site == lock._site:
                # reentrant or same-site sibling: never an ordering edge
                acq.count += 1
                if lock._uid not in acq.uids:
                    acq.uids.append(lock._uid)
                return
        if held:
            holder = held[-1].site
            if holder != lock._site:
                witness = (
                    f"thread '{threading.current_thread().name}' acquired "
                    f"{lock._site} while holding {holder}"
                )
                with self._mu:
                    self._edges.setdefault(holder, {}).setdefault(
                        lock._site, witness
                    )
        held.append(_Acquisition(lock._site, lock._uid))

    def _note_release(self, lock: TrackedLock, full: bool = False) -> None:
        held = getattr(self._local, "held", None)
        if held:
            # match by lock IDENTITY first: a site-only match could hit a
            # same-site SIBLING's entry (and a handoff release would then
            # corrupt this thread's real holding instead of tainting the
            # handed-off lock)
            for i in range(len(held) - 1, -1, -1):
                if lock._uid in held[i].uids:
                    held[i].count -= 1
                    if full or held[i].count <= 0:
                        del held[i]
                    elif held[i].count < len(held[i].uids):
                        # a pooled sibling fully released (count dropped
                        # below the distinct instances tracked): retire its
                        # uid; reentrant releases of one lock keep the uid
                        held[i].uids.remove(lock._uid)
                    return
            # uid unknown but a same-site entry carries surplus pooled
            # acquisitions (count > distinct uids): attribute the release
            # there rather than tainting a legitimately-pooled sibling
            for i in range(len(held) - 1, -1, -1):
                if (
                    held[i].site == lock._site
                    and held[i].count > len(held[i].uids)
                ):
                    held[i].count -= 1
                    if full or held[i].count <= 0:
                        del held[i]
                    return
        # released on a thread that never acquired it: cross-thread
        # handoff. Taint the uid so every thread purges the leaked entry
        # (see _tainted_uids) — ownership analysis cannot model a lock
        # used as a semaphore.
        with self._mu:
            self._tainted_uids.add(lock._uid)

    # -- analysis ----------------------------------------------------------

    def edges(self) -> Dict[str, Dict[str, str]]:
        with self._mu:
            return {a: dict(bs) for a, bs in self._edges.items()}

    def cycles(self) -> List[List[str]]:
        """Site cycles in the acquisition-order graph (each returned list
        is one cycle, sites in order; the inversion witnesses come from
        report())."""
        graph = self.edges()
        sccs = _sccs({a: list(bs) for a, bs in graph.items()})
        return [sorted(s) for s in sccs if len(s) > 1]

    def report(self) -> str:
        cycles = self.cycles()
        if not cycles:
            return "lockwatch: no acquisition-order cycles"
        graph = self.edges()
        lines = [
            f"lockwatch: {len(cycles)} potential deadlock(s) — lock "
            "acquisition-order cycle(s) detected:"
        ]
        for cycle in cycles:
            lines.append("  cycle: " + " <-> ".join(cycle))
            members = set(cycle)
            for a in cycle:
                for b, witness in sorted(graph.get(a, {}).items()):
                    if b in members:
                        lines.append(f"    {witness}")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()


def _sccs(graph: Dict[str, List[str]]) -> List[Set[str]]:
    """Iterative Tarjan (shared shape with analysis/layering, duplicated so
    the runtime watcher stays importable without the analysis package)."""
    index: Dict[str, int] = {}
    lowlink: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]
    nodes = set(graph)
    for targets in graph.values():
        nodes.update(targets)
    full = {n: [t for t in graph.get(n, [])] for n in nodes}

    for root in full:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, ei = work[-1]
            if ei == 0:
                index[node] = lowlink[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            targets = full[node]
            while ei < len(targets):
                target = targets[ei]
                ei += 1
                if target not in index:
                    work[-1] = (node, ei)
                    work.append((target, 0))
                    advanced = True
                    break
                if target in on_stack:
                    lowlink[node] = min(lowlink[node], index[target])
            if advanced:
                continue
            work.pop()
            if lowlink[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)
            if work:
                parent, _ = work[-1]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
    return out


# -- global instance (conftest arming) ------------------------------------

GLOBAL = LockWatch()


def arm(spec: str = "", default_on: bool = True) -> bool:
    """Install the global watcher per a KARPENTER_LOCKWATCH spec string
    (truthy/falsy spellings shared with obs/envflags; empty -> default_on).
    The CALLER reads the environment — conftest.py arms this before the
    package (and its module-level locks) loads, and this module stays
    stdlib-only with no env access of its own (env-flags rule)."""
    spec = (spec or "").strip().lower()
    if spec in ("0", "false", "off", "no"):
        return False
    if spec in ("1", "true", "on", "yes") or default_on:
        GLOBAL.install()
        return True
    return False
