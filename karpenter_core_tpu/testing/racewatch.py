"""Eraser-style lockset data-race detector — the Python analog of
`go test -race` (ISSUE 13 tentpole).

Lockwatch catches acquisition-ORDER inversions; what it cannot see is a
field touched by two threads under no common lock at all — the actual
data race the Go reference's `-race`-instrumented presubmit exists for.
This module closes that gap with the classic Eraser algorithm
(Savage et al., SOSP '97) over the package's own lock machinery:

  * **Discovery** rides lockwatch: every `threading.Lock`/`RLock`
    allocated from package code already becomes a ``TrackedLock`` proxy
    (allocation-frame filter); racewatch registers an allocation hook and,
    when the allocating frame is a method (``self`` in its locals) of a
    package class, instruments THAT class — a class that owns a lock has
    concurrent state worth watching, everything else pays nothing.
  * **Instrumentation** wraps the class's ``__setattr__`` and
    ``__getattribute__``; only attribute names seen WRITTEN on a tracked
    instance are recorded on the read path (method lookups early-out on a
    set-membership test), and only sampled instances are tracked at all.
  * **State machine** per (object, field), exactly Eraser's:

        virgin -> exclusive (first thread only; no lockset yet — object
                  construction and single-thread use never report)
               -> shared (read by a second thread; candidate lockset
                  initialized from the accessor's held locks, refined on
                  every later read — an EMPTY set here does NOT report:
                  read-only sharing after initialization is fine)
               -> shared-modified (written while shared, or written by a
                  second thread; the lockset keeps intersecting with the
                  accessor's held set and the first empty intersection IS
                  the race — reported once, with both access stacks)

    Held-lock sets come from lockwatch (`held_lock_uids()` — lock
    *instance* identity, so sibling locks from one allocation site don't
    alias).
  * **Overhead bounds**: a sampling knob (track every Nth instance per
    class) and a per-field access cap (a field stops updating after
    ``access_cap`` recorded accesses — by then its lockset has long
    converged). Defaults track everything with cap 128; the race-smoke CI
    lane forces sampling off and the cap up.

Arming: tests/conftest.py calls ``arm(os.environ.get(...))`` right after
lockwatch (this module does no env access of its own — env-flags rule) and
fails the session on unsuppressed races in ``pytest_sessionfinish``.
``KARPENTER_RACEWATCH=0`` opts out; ``KARPENTER_RACEWATCH_SAMPLE=<n>`` and
``KARPENTER_RACEWATCH_CAP=<n>`` tune the bounds (cap 0 = unlimited).

False-positive policy (docs/static-analysis.md has the full hierarchy):
benign races are suppressed by ``suppress("Class.field", reason)`` —
audited, centrally, never inline; the shipped suppression table must stay
justified and the real suite must report zero unsuppressed races.
"""
from __future__ import annotations

import os
import sys
import threading
import weakref
from typing import Dict, FrozenSet, List, Optional, Tuple

from karpenter_core_tpu.testing import lockwatch

_allocate_lock = threading._allocate_lock

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG_NAME = os.path.basename(_PKG_DIR)

# Eraser states
VIRGIN = 0  # implicit: no entry yet
EXCLUSIVE = 1
SHARED = 2
SHARED_MODIFIED = 3

_STATE_NAMES = {
    EXCLUSIVE: "exclusive",
    SHARED: "shared",
    SHARED_MODIFIED: "shared-modified",
}


def _pkg_stack(skip: int, limit: int = 4) -> Tuple[str, ...]:
    """Up to `limit` package-code frames above `skip`, innermost first —
    the per-access provenance a race report renders. Single-frame reads
    via sys._getframe keep this cheap enough for capped recording."""
    out: List[str] = []
    depth = skip
    while len(out) < limit:
        try:
            frame = sys._getframe(depth)
        except ValueError:
            break
        depth += 1
        filename = frame.f_code.co_filename
        if _PKG_DIR in filename and "racewatch" not in filename:
            rel = os.path.relpath(filename, os.path.dirname(_PKG_DIR))
            out.append(f"{rel}:{frame.f_lineno} in {frame.f_code.co_name}")
        elif out:
            break  # left the package: the interesting suffix is complete
        if depth > skip + 14:
            break
    return tuple(out)


class _Access:
    """One recorded access: who, where, holding what."""

    __slots__ = ("thread", "op", "stack", "held")

    def __init__(self, op: str, held: FrozenSet[int]) -> None:
        self.thread = threading.current_thread().name
        self.op = op
        self.stack = _pkg_stack(skip=4)
        self.held = held

    def render(self, watch: "RaceWatch") -> str:
        locks = (
            ", ".join(sorted(watch._lockwatch.site_of_uid(u) for u in self.held))
            or "no locks"
        )
        where = " <- ".join(self.stack) or "<non-package frame>"
        return f"{self.op} by thread '{self.thread}' holding [{locks}] at {where}"


class _FieldState:
    __slots__ = ("state", "owner", "lockset", "accesses", "last_write",
                 "last_access", "reported")

    def __init__(self, owner_thread_id: int) -> None:
        self.state = EXCLUSIVE
        self.owner = owner_thread_id
        self.lockset: Optional[FrozenSet[int]] = None
        self.accesses = 0
        self.last_write: Optional[_Access] = None
        self.last_access: Optional[_Access] = None
        self.reported = False


class Race:
    """One candidate race: a field whose candidate lockset emptied while
    shared-modified, with the two accesses that proved it."""

    def __init__(self, cls_name: str, field: str, prior: Optional[_Access],
                 current: _Access, state: int) -> None:
        self.key = f"{cls_name}.{field}"
        self.cls_name = cls_name
        self.field = field
        self.prior = prior
        self.current = current
        self.state = state

    def render(self, watch: "RaceWatch") -> str:
        lines = [
            f"candidate race on {self.key} "
            f"(state {_STATE_NAMES.get(self.state, self.state)}, "
            "candidate lockset empty):"
        ]
        if self.prior is not None:
            lines.append(f"    prior:   {self.prior.render(watch)}")
        lines.append(f"    current: {self.current.render(watch)}")
        return "\n".join(lines)


class RaceWatch:
    """Lockset race detector over lock-owning package classes.

    Consumes a LockWatch for lock allocation events and per-thread held
    sets; instruments owning classes' attribute protocol; maintains the
    Eraser state machine per (instance, field)."""

    def __init__(self, lock_watch: Optional[lockwatch.LockWatch] = None,
                 sample: int = 1, access_cap: int = 128,
                 class_filter=None, access_filter=None) -> None:
        self._mu = _allocate_lock()
        self._lockwatch = lock_watch or lockwatch.LockWatch()
        self.sample = max(1, int(sample))
        self.access_cap = int(access_cap)  # <=0 means unlimited
        self._class_filter = class_filter or _default_class_filter
        # when set, fn(filename) -> bool decides whether an access frame is
        # recorded at all. The GLOBAL watcher records PACKAGE frames only:
        # a test reading a counter after join() is synchronized by the join
        # — an edge Eraser cannot see — and would be a guaranteed false
        # positive. Standalone instances (None) record everything, so
        # fixture tests can drive the state machine from test code.
        self._access_filter = access_filter
        # type -> (orig_setattr, orig_getattribute); identity-keyed
        self._instrumented: Dict[type, Tuple[object, object]] = {}
        # per-class allocation counter driving the sampling knob
        self._alloc_counts: Dict[type, int] = {}
        # id(obj) -> {field: _FieldState}; a weakref finalizer retires the
        # entry so a recycled id can't inherit a dead object's states
        self._objects: Dict[int, Dict[str, _FieldState]] = {}
        self._object_refs: Dict[int, weakref.ref] = {}
        # weakref callbacks fire at arbitrary allocation points — possibly
        # while THIS thread already holds self._mu — so they only append
        # (lock-free) here; _note drains the list under the lock
        self._dead: List[int] = []
        # per-class set of attribute names ever WRITTEN on a tracked
        # instance: the read path's early-out (method/descriptor lookups
        # miss this set and record nothing)
        self._fields_of: Dict[type, set] = {}
        self._races: List[Race] = []
        self._suppressed_hits: Dict[str, int] = {}
        self.suppressions: Dict[str, str] = {}  # "Class.field" -> reason
        self._installed = False
        self.tracked_instances = 0
        self.recorded_accesses = 0

    # -- wiring ------------------------------------------------------------

    def install(self) -> "RaceWatch":
        """Hook lock allocations (idempotent). The LockWatch itself must
        be installed separately (conftest arms lockwatch first)."""
        with self._mu:
            if self._installed:
                return self
            self._installed = True
        self._lockwatch.add_allocation_hook(self._on_lock_allocated)
        return self

    def uninstall(self) -> None:
        """Restore every instrumented class's attribute protocol and drop
        tracked-object state (a wrapper a subclass materialized keeps
        pointing at the closure — an empty object table makes it inert)."""
        with self._mu:
            instrumented = dict(self._instrumented)
            self._instrumented.clear()
            self._objects.clear()
            self._object_refs.clear()
            self._installed = False
        for cls, (orig_set, orig_get) in instrumented.items():
            cls.__setattr__ = orig_set
            cls.__getattribute__ = orig_get

    def suppress(self, key: str, reason: str) -> None:
        """Mark `Class.field` as an audited benign race. Suppressions are
        central and reasoned — never sprayed at call sites."""
        self.suppressions[key] = reason

    # -- discovery ---------------------------------------------------------

    def _on_lock_allocated(self, lock, frame) -> None:
        if frame is None:
            return
        owner = frame.f_locals.get("self")
        if owner is None:
            return
        cls = type(owner)
        if not self._class_filter(cls):
            return
        self._instrument_class(cls)
        with self._mu:
            n = self._alloc_counts.get(cls, 0)
            self._alloc_counts[cls] = n + 1
            if n % self.sample:
                return
        self.track_instance(owner)

    def track_instance(self, obj) -> None:
        """Explicitly start tracking `obj` (tests seed pre-fix
        interleavings this way; the allocation hook is the normal path).
        Instruments the class if the discovery hook hasn't already."""
        cls = type(obj)
        self._instrument_class(cls)
        oid = id(obj)
        dead = self._dead
        try:
            # the callback must NOT take self._mu: GC can fire it while
            # this very thread holds the lock — append is lock-free and
            # _note drains
            ref = weakref.ref(obj, lambda _r, oid=oid: dead.append(oid))
        except TypeError:
            return  # no weakref support: tracking would leak the object
        with self._mu:
            # drain retirements FIRST: a dead object's id can be recycled
            # by this very instance, and the stale entry would swallow the
            # registration (its old-owner states then misread the new
            # object's single-threaded construction as cross-thread)
            if self._dead:
                self._drain_dead_locked()
            if oid in self._objects:
                return
            self._objects[oid] = {}
            self._object_refs[oid] = ref
            self.tracked_instances += 1

    def _drain_dead_locked(self) -> None:
        while self._dead:
            oid = self._dead.pop()
            self._objects.pop(oid, None)
            self._object_refs.pop(oid, None)

    def _instrument_class(self, cls: type) -> None:
        with self._mu:
            if cls in self._instrumented:
                return
            orig_set = cls.__setattr__
            orig_get = cls.__getattribute__
            if getattr(orig_set, "__racewatch__", None) is self or getattr(
                orig_get, "__racewatch__", None
            ) is self:
                # a subclass inheriting an instrumented base's wrappers:
                # already effectively instrumented — wrapping again would
                # record every access twice (burning the per-field cap at
                # 2x) and pin the base's wrapper onto the subclass forever
                return
            self._instrumented[cls] = (orig_set, orig_get)
            fields = self._fields_of.setdefault(cls, set())
        watch = self
        objects = self._objects

        def __setattr__(obj, name, value, _orig=orig_set):
            _orig(obj, name, value)
            states = objects.get(id(obj))
            if states is not None:
                fields.add(name)
                watch._note(obj, states, name, "write")

        def __getattribute__(obj, name, _orig=orig_get):
            value = _orig(obj, name)
            if name in fields:
                states = objects.get(id(obj))
                if states is not None:
                    watch._note(obj, states, name, "read")
            return value

        __setattr__.__racewatch__ = watch
        __getattribute__.__racewatch__ = watch
        cls.__setattr__ = __setattr__
        cls.__getattribute__ = __getattribute__

    # -- the state machine -------------------------------------------------

    def _note(self, obj, states: Dict[str, _FieldState], field: str,
              op: str) -> None:
        if self._access_filter is not None and not self._access_filter(
            sys._getframe(2).f_code.co_filename
        ):
            return
        tid = threading.get_ident()
        with self._mu:
            if self._dead:
                self._drain_dead_locked()
            if self._objects.get(id(obj)) is not states:
                # the wrapper raced a retirement (or a recycled id hit a
                # stale entry): this states dict is not this object's
                return
            st = states.get(field)
            if st is None:
                states[field] = st = _FieldState(tid)
            if st.reported or (
                self.access_cap > 0 and st.accesses >= self.access_cap
            ):
                return
            st.accesses += 1
            self.recorded_accesses += 1
            held = self._lockwatch.held_lock_uids()
            acc = _Access(op, held)
            if st.state == EXCLUSIVE:
                if tid == st.owner:
                    pass  # still single-threaded: construction/handoff-free
                elif op == "read":
                    st.state = SHARED
                    st.lockset = held
                else:
                    st.state = SHARED_MODIFIED
                    st.lockset = held
            else:
                st.lockset = (
                    held if st.lockset is None else st.lockset & held
                )
                if op == "write" and st.state == SHARED:
                    st.state = SHARED_MODIFIED
            if (
                st.state == SHARED_MODIFIED
                and st.lockset is not None
                and not st.lockset
                and not st.reported
            ):
                st.reported = True
                self._report(obj, field, st, acc)
            if op == "write":
                st.last_write = acc
            st.last_access = acc

    def _report(self, obj, field: str, st: _FieldState, acc: _Access) -> None:
        cls_name = type(obj).__name__
        key = f"{cls_name}.{field}"
        if key in self.suppressions:
            self._suppressed_hits[key] = self._suppressed_hits.get(key, 0) + 1
            return
        if any(r.key == key for r in self._races):
            return  # one report per (class, field): instances would spam
        prior = st.last_write if acc.op == "read" else (
            st.last_write or st.last_access
        )
        self._races.append(Race(cls_name, field, prior, acc, st.state))

    # -- reporting ---------------------------------------------------------

    def races(self) -> List[Race]:
        with self._mu:
            return list(self._races)

    def report(self) -> str:
        races = self.races()
        if not races:
            return "racewatch: no candidate data races"
        lines = [
            f"racewatch: {len(races)} candidate data race(s) — field(s) "
            "accessed by multiple threads under no common lock:"
        ]
        for race in races:
            lines.append("  " + race.render(self).replace("\n", "\n  "))
        return "\n".join(lines)

    def stats(self) -> Dict[str, object]:
        with self._mu:
            return {
                "tracked_classes": len(self._instrumented),
                "tracked_instances": self.tracked_instances,
                "recorded_accesses": self.recorded_accesses,
                "races": len(self._races),
                "suppressed_hits": dict(self._suppressed_hits),
                "sample": self.sample,
                "access_cap": self.access_cap,
            }

    def reset(self) -> None:
        with self._mu:
            self._races.clear()
            self._suppressed_hits.clear()
            for states in self._objects.values():
                states.clear()


def _default_class_filter(cls: type) -> bool:
    """Instrument package classes only — and never the watchers' own."""
    module = getattr(cls, "__module__", "") or ""
    if not module.startswith(_PKG_NAME):
        return False
    return "lockwatch" not in module and "racewatch" not in module


# -- global instance (conftest arming) --------------------------------------

def _pkg_access_filter(filename: str) -> bool:
    """Record accesses made from package source only (mirrors lockwatch's
    allocation-frame filter): accesses from test/harness frames are often
    synchronized by thread join/start edges Eraser cannot see."""
    return _PKG_DIR in filename


# the global racewatch rides the global lockwatch: one allocation filter,
# one held-set source, one patch of threading.Lock/RLock
GLOBAL = RaceWatch(lock_watch=lockwatch.GLOBAL, access_filter=_pkg_access_filter)

# Audited benign-race suppressions for the shipped package (the suppression
# hierarchy's racewatch tier — docs/static-analysis.md). Every entry must
# explain WHY the unlocked access is sound. The common shape here is a
# LATCHING config flag: written under the owner's lock (torn multi-field
# configuration is impossible), but read lock-free on a hot path where a
# lock acquire per call would be a real regression — CPython attribute
# loads are atomic, and the worst case of a stale read is one extra or
# missing record, never corruption.
for _key, _reason in {
    "LogSink.level": (
        "the one hot-path gate: compared on EVERY log call site before "
        "anything is built; writes latch under LogSink._mu (configure/"
        "disable); a stale level costs one mis-gated record"
    ),
    "LogSink.fmt": (
        "render-format latch written under LogSink._mu at configure time, "
        "read lock-free in emit(); stale read renders one record in the "
        "previous format"
    ),
    "LogSink.stream": (
        "line-sink latch, same configure-under-lock / lock-free-emit "
        "shape; emit() snapshots it into a local before use"
    ),
    "FlightRecorder.enabled": (
        "latching bool read once per solve (the 'disabled = one flag "
        "check' contract); writes latch under FlightRecorder._mu; a stale "
        "read records or skips one solve at the enable/disable boundary"
    ),
    "FlightRecorder.dump_dir": (
        "written under FlightRecorder._mu at enable time, read at dump "
        "time; dumps are best-effort by contract"
    ),
    "Tracer.enabled": (
        "the tracer's own 'disabled = one flag check' gate, read on every "
        "span()/add_span()/instant() call site AND per host dispatch "
        "(frame trace-key gate, ISSUE 15); writes latch under Tracer._mu "
        "(enable/disable); a stale read costs one span recorded or "
        "skipped at the arm/disarm boundary"
    ),
}.items():
    GLOBAL.suppress(_key, _reason)


def arm(spec: str = "", default_on: bool = True, sample: str = "",
        cap: str = "") -> bool:
    """Install the global detector per a KARPENTER_RACEWATCH spec (same
    truthy/falsy grammar as lockwatch.arm; the CALLER reads the env —
    this module stays env-free per the env-flags rule). `sample`/`cap`
    are the raw KARPENTER_RACEWATCH_{SAMPLE,CAP} strings."""
    spec = (spec or "").strip().lower()
    if spec in ("0", "false", "off", "no"):
        return False
    if not (spec in ("1", "true", "on", "yes") or default_on):
        return False
    try:
        GLOBAL.sample = max(1, int(sample)) if sample.strip() else GLOBAL.sample
    except ValueError:
        pass
    try:
        GLOBAL.access_cap = int(cap) if cap.strip() else GLOBAL.access_cap
    except ValueError:
        pass
    GLOBAL.install()
    return True
