"""Deterministic churn schedules: seeded arrival/termination/resize point
processes over a bounded horizon.

The grammar (docs/soak.md): three independent Poisson processes — ARRIVE
(rate `arrival_rate` pods/s, each event carrying a scenario drawn from the
weighted `mix` and a replica count), TERMINATE (rate `termination_rate`,
each event deleting one bound pod), RESIZE (rate `resize_rate`, each event
replacing one bound pod with a re-sized replica, i.e. a simultaneous
free + arrive). Rates are modulated sinusoidally — lambda(t) = base *
(1 + burst_amplitude * sin(2*pi*t / burst_period_s)) — and sampled by
thinning against lambda_max, so the whole schedule is a pure function of
(config, seed): the soak bench, the parity suite, and a field repro of a
soak incident all see byte-identical event streams.

The generator emits WHAT happens and WHEN, never to WHOM: target selection
(which bound pod a termination kills) needs cluster state the generator
must not know, so the driver resolves targets with its own seeded rng.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Iterator, List

import numpy as np

ARRIVE = "arrive"
TERMINATE = "terminate"
RESIZE = "resize"

DEFAULT_MIX: Dict[str, float] = {
    "generic": 0.45,
    "bulk": 0.25,
    "spread": 0.2,
    "anti": 0.1,
}


@dataclass(frozen=True)
class ChurnEvent:
    at: float  # seconds from soak start
    kind: str  # ARRIVE | TERMINATE | RESIZE
    scenario: str = ""  # ARRIVE only: scenarios.SCENARIOS key
    count: int = 1  # ARRIVE only: replicas created together


@dataclass
class ChurnConfig:
    seed: int = 0
    duration_s: float = 60.0
    arrival_rate: float = 6.0  # mean pod-arrival events/s
    termination_rate: float = 4.0  # mean deletions/s (no-ops while unbound)
    resize_rate: float = 0.4  # mean replace-with-resized/s
    burst_period_s: float = 12.0
    burst_amplitude: float = 0.6  # 0 = flat; 1 = rate swings 0..2x
    mix: Dict[str, float] = field(default_factory=lambda: dict(DEFAULT_MIX))
    bulk_max: int = 10  # bulk arrivals carry 3..bulk_max replicas
    initial_pods: int = 24  # warm-up batch at t=0 (generic)
    # pre-existing cluster nodes: a soak measures STEADY-STATE churn over a
    # running cluster, not genesis — and seeding the existing axis inside a
    # stable pow2 encode bucket keeps the solve geometry (and with it the
    # incremental path's residency) from re-minting on every early launch
    initial_nodes: int = 12

    def __post_init__(self):
        if not 0.0 <= self.burst_amplitude <= 1.0:
            raise ValueError("burst_amplitude must be in [0, 1]")
        if self.duration_s <= 0:
            raise ValueError("duration_s must be positive")
        if any(w < 0 for w in self.mix.values()) or not any(self.mix.values()):
            raise ValueError("mix weights must be >= 0 with a positive sum")


class ChurnGenerator:
    def __init__(self, config: ChurnConfig):
        self.config = config

    def rate_at(self, t: float, base: float) -> float:
        c = self.config
        return base * (
            1.0 + c.burst_amplitude * math.sin(2.0 * math.pi * t / c.burst_period_s)
        )

    def _thinned_times(self, rng: np.random.Generator, base: float) -> List[float]:
        """Inhomogeneous-Poisson event times by thinning: candidates at
        lambda_max, kept with probability lambda(t)/lambda_max."""
        c = self.config
        out: List[float] = []
        if base <= 0:
            return out
        lam_max = base * (1.0 + c.burst_amplitude)
        t = 0.0
        while True:
            t += rng.exponential(1.0 / lam_max)
            if t >= c.duration_s:
                return out
            if rng.uniform() * lam_max <= self.rate_at(t, base):
                out.append(t)

    def events(self) -> List[ChurnEvent]:
        """The full schedule, sorted by time (stable tie-break on kind so
        equal-time events replay in one deterministic order)."""
        c = self.config
        # one child stream per process: adding resize events must not
        # reshuffle the arrival times a previous soak run recorded
        arr_rng, term_rng, rsz_rng, mix_rng = (
            np.random.default_rng(s)
            for s in np.random.SeedSequence(c.seed).spawn(4)
        )
        events: List[ChurnEvent] = []
        if c.initial_pods:
            events.append(ChurnEvent(0.0, ARRIVE, "generic", c.initial_pods))
        names = sorted(c.mix)
        weights = np.array([c.mix[k] for k in names], dtype=float)
        weights /= weights.sum()
        for t in self._thinned_times(arr_rng, c.arrival_rate):
            scenario = names[int(mix_rng.choice(len(names), p=weights))]
            count = (
                int(mix_rng.integers(3, max(c.bulk_max, 3) + 1))
                if scenario == "bulk"
                else 1
            )
            events.append(ChurnEvent(t, ARRIVE, scenario, count))
        events.extend(
            ChurnEvent(t, TERMINATE) for t in self._thinned_times(term_rng, c.termination_rate)
        )
        events.extend(
            ChurnEvent(t, RESIZE) for t in self._thinned_times(rsz_rng, c.resize_rate)
        )
        events.sort(key=lambda e: (e.at, e.kind))
        return events

    def __iter__(self) -> Iterator[ChurnEvent]:
        return iter(self.events())
