"""Churn load generation: seeded, deterministic sustained-traffic processes
that drive the REAL operator loop (batcher -> provisioner -> solver -> bind),
not the solver directly — the first subsystem that exercises the control
plane under time instead of one call (ROADMAP open item 2).

Three pieces:

  * churn.ChurnGenerator — a deterministic event schedule (pod arrivals,
    terminations, resizes) from seeded Poisson processes with sinusoidal
    burst modulation and a weighted scenario mix;
  * scenarios — pod builders over a BOUNDED label vocabulary, so the
    solver's dictionary geometry stabilizes and steady-state churn exercises
    the incremental delta re-solve path (solver/incremental.py) instead of
    minting a new compiled program per batch;
  * driver.SoakDriver — applies the schedule to a full operator (fake cloud
    provider + in-memory apiserver), plays kubelet for nominated pods via
    the provisioner bind feed, and reports SLOs (admission->bind p50/p99,
    queue depth, incremental-solve hit ratio) from real metrics exposition.

Layering: loadgen may depend on controllers/solver/operator; NOTHING may
depend on loadgen (analysis/config.py DEFAULT_LAYERING).
"""
from karpenter_core_tpu.loadgen.churn import ChurnConfig, ChurnEvent, ChurnGenerator
from karpenter_core_tpu.loadgen.driver import SoakDriver, SoakReport
from karpenter_core_tpu.loadgen.scenarios import SCENARIOS, ScenarioMixer

__all__ = [
    "ChurnConfig",
    "ChurnEvent",
    "ChurnGenerator",
    "ScenarioMixer",
    "SCENARIOS",
    "SoakDriver",
    "SoakReport",
]
