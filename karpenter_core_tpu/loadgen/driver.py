"""SoakDriver: applies a churn schedule to a FULL operator and reports SLOs.

The driver is the world around the control plane: it is the workload
(creating, deleting, and resizing pods on the in-memory apiserver at the
generator's pace) and the kubelet/kube-scheduler (binding pods the
provisioning loop nominated, via ProvisioningController.bind_listeners —
the reference leaves binding to the real scheduler, and without it every
pod would stay pending forever and the admission->bind SLO would measure
nothing). Everything in between — watch pumps, batcher windows, solves,
launches — is the REAL operator loop.

Two run modes mirror the operator's:

  run()       realtime: op.start() background pumps + singletons, events
              applied on the wall clock — the soak bench (hack/soak.py)
  run_steps() virtual time: a FakeClock advanced event-to-event with
              synchronous op.step() passes — deterministic, fast, what the
              test suite uses

SLOs come from real metrics exposition (the provisioner's
karpenter_admission_to_bind_seconds histogram and karpenter_pending_pods
gauge), baseline-diffed so a soak reports ONLY its own window; the
incremental-solve hit ratio comes from karpenter_incremental_screen_total;
per-mode prescreen device timings come from solver.phase.prescreen tracer
spans (the solver runs with profile_phases=True so the span covers the
device execution, not just the dispatch).
"""
from __future__ import annotations

import statistics
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from karpenter_core_tpu.api.labels import (
    LABEL_CAPACITY_TYPE,
    LABEL_NODE_INITIALIZED,
    PROVISIONER_NAME_LABEL_KEY,
)
from karpenter_core_tpu.api.settings import Settings
from karpenter_core_tpu.chaos import CHAOS_INJECTED_TOTAL
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.controllers.provisioning.provisioner import (
    ADMISSION_TO_BIND,
    PENDING_PODS,
)
from karpenter_core_tpu.loadgen.churn import ARRIVE, RESIZE, TERMINATE, ChurnConfig, ChurnGenerator
from karpenter_core_tpu.loadgen.scenarios import CPU_STEPS, ScenarioMixer
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_TOPOLOGY_ZONE,
)
from karpenter_core_tpu.obs import TRACER
from karpenter_core_tpu.obs.log import get_logger
from karpenter_core_tpu.operator import new_operator
from karpenter_core_tpu.solver.incremental import INCREMENTAL_SCREEN_TOTAL
from karpenter_core_tpu.solver.tpu_solver import TPUSolver
from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner

LOG = get_logger("karpenter.loadgen")

INC_OUTCOMES = (
    "refresh", "full_miss", "full_wide", "full_shape", "full_gated", "full_deg",
)
_PRESCREEN_SPAN = "solver.phase.prescreen"


@dataclass
class SoakReport:
    duration_s: float = 0.0
    pods_created: int = 0
    pods_terminated: int = 0
    resizes: int = 0
    binds: int = 0
    unbound_at_end: int = 0
    machines_launched: int = 0
    admission_count: int = 0
    admission_p50_s: Optional[float] = None
    admission_p99_s: Optional[float] = None
    pending_max: float = 0.0
    pending_mean: float = 0.0
    inc_outcomes: Dict[str, int] = field(default_factory=dict)
    resolve_ratio: Optional[float] = None  # refresh / all prescreen solves
    prescreen_refresh_med_ms: Optional[float] = None
    prescreen_full_med_ms: Optional[float] = None
    prescreen_cold: int = 0  # compile-paying dispatches excluded from medians
    device_med_ms: Optional[float] = None
    chaos_injected: int = 0
    loops_alive: bool = True

    def as_columns(self, prefix: str = "churn_") -> Dict[str, object]:
        """Flat BENCH_*-style columns (docs/PERF.md 'churn columns')."""
        cols = {
            f"{prefix}duration_s": round(self.duration_s, 1),
            f"{prefix}pods_created": self.pods_created,
            f"{prefix}pods_terminated": self.pods_terminated,
            f"{prefix}resizes": self.resizes,
            f"{prefix}binds": self.binds,
            f"{prefix}unbound_at_end": self.unbound_at_end,
            f"{prefix}machines": self.machines_launched,
            f"{prefix}admission_count": self.admission_count,
            f"{prefix}admission_p50_s": self.admission_p50_s,
            f"{prefix}admission_p99_s": self.admission_p99_s,
            f"{prefix}pending_max": self.pending_max,
            f"{prefix}pending_mean": round(self.pending_mean, 1),
            f"{prefix}resolve_ratio": (
                round(self.resolve_ratio, 3) if self.resolve_ratio is not None else None
            ),
            f"{prefix}prescreen_refresh_med_ms": self.prescreen_refresh_med_ms,
            f"{prefix}prescreen_full_med_ms": self.prescreen_full_med_ms,
            f"{prefix}prescreen_cold": self.prescreen_cold,
            f"{prefix}device_med_ms": self.device_med_ms,
            f"{prefix}chaos_injected": self.chaos_injected,
            f"{prefix}loops_alive": self.loops_alive,
        }
        for outcome in INC_OUTCOMES:
            cols[f"{prefix}inc_{outcome}"] = self.inc_outcomes.get(outcome, 0)
        return cols


class SoakDriver:
    def __init__(
        self,
        config: ChurnConfig,
        instance_type_count: int = 8,
        solver=None,
        settings: Optional[Settings] = None,
        clock=None,
        max_nodes: int = 256,
        tail_timeout_s: float = 10.0,
    ):
        self.config = config
        self.clock = clock or time.time
        self.tail_timeout_s = tail_timeout_s
        self.generator = ChurnGenerator(config)
        # independent child streams: target selection must not perturb the
        # generator's schedule, and the mixer's pod shapes must not depend
        # on how many terminations found a target
        mix_rng, self._target_rng = (
            np.random.default_rng(s)
            for s in np.random.SeedSequence((config.seed << 8) ^ 0x50AC).spawn(2)
        )
        self.mixer = ScenarioMixer(mix_rng)
        self.solver = solver or TPUSolver(
            max_nodes=max_nodes, screen_mode="prescreen", profile_phases=True
        )
        self.cloud = fake.FakeCloudProvider(fake.instance_types(instance_type_count))
        self.op = new_operator(
            self.cloud,
            # capped batches: steady-state passes stay in ONE solve geometry
            # (stable pow2 item bucket), so a slow pass can't inflate the
            # next batch into a fresh compile — see Settings.batch_max_pods
            settings=settings
            or Settings(
                batch_idle_duration=0.05, batch_max_duration=0.5,
                batch_max_pods=16,
            ),
            solver=self.solver,
            clock=self.clock,
        )
        self.op.provisioning.bind_listeners.append(self._on_bind)
        # the report's per-mode prescreen medians and device median read
        # solver.phase.* spans — arm tracing the way bench.py does
        TRACER.enable()
        self._bind_q: deque = deque()  # (ns, name, node) from the reconcile thread
        self.report = SoakReport()
        self._pending_samples: List[float] = []
        self._prescreen_ms: Dict[str, List[float]] = {"refresh": [], "full": []}
        self._device_ms: List[float] = []
        self._trace_mark = 0

    # -- kubelet analog ----------------------------------------------------

    def _on_bind(self, pod, node_name: str) -> None:
        self._bind_q.append((pod.metadata.namespace, pod.metadata.name, node_name))

    def drain_binds(self) -> int:
        """Apply queued nominations as bindings (set spec.node_name), the
        way the kube-scheduler + kubelet would. Best-effort per pod: a pod
        deleted between nomination and bind is simply gone."""
        bound = 0
        while self._bind_q:
            ns, name, node = self._bind_q.popleft()
            try:
                pod = self.op.kube_client.get("Pod", ns, name)
                if pod is None or pod.spec.node_name:
                    continue
                pod.spec.node_name = node
                self.op.kube_client.update(pod)
                bound += 1
            except Exception:  # noqa: BLE001 — chaos may sit on the client
                # put it back for the next drain: nominations are precious
                self._bind_q.append((ns, name, node))
                break
        self.report.binds += bound
        return bound

    # -- steady-state seed -------------------------------------------------

    def _seed_cluster(self) -> None:
        """Provisioner + `initial_nodes` pre-existing READY nodes, created
        before the first event: a soak measures steady-state churn over a
        RUNNING cluster, not genesis. Seeding also pins the solve geometry:
        the encoder buckets the existing-node axis pow2, so a cluster grown
        one launch at a time crosses bucket edges (8 -> 16 -> 32) during the
        measured window — each crossing mints a fresh compiled program AND
        evicts the incremental path's resident verdict tensor. Starting
        inside a stable bucket turns those into warmup-covered geometries."""
        self.op.kube_client.create(make_provisioner(name="default"))
        universe = self.cloud.instance_types
        zones = ("test-zone-1", "test-zone-2", "test-zone-3")
        for i in range(self.config.initial_nodes):
            # cycle the BIGGER half of the ladder: seed capacity is the
            # churn's landing zone, and 1-cpu seeds would just be noise rows
            it = universe[len(universe) // 2 + i % max(len(universe) - len(universe) // 2, 1)]
            node = make_node(
                name=f"seed-node-{i}",
                labels={
                    PROVISIONER_NAME_LABEL_KEY: "default",
                    LABEL_NODE_INITIALIZED: "true",
                    LABEL_INSTANCE_TYPE_STABLE: it.name,
                    LABEL_TOPOLOGY_ZONE: zones[i % len(zones)],
                    LABEL_CAPACITY_TYPE: "on-demand",
                },
                capacity=dict(it.capacity),
                provider_id=f"fake:///seed-node-{i}",
            )
            self.op.kube_client.create(node)

    # -- event application -------------------------------------------------

    def _bound_pods(self) -> List:
        return self.op.kube_client.list(
            "Pod", field_filter=lambda p: bool(p.spec.node_name)
        )

    def apply_event(self, event) -> None:
        if event.kind == ARRIVE:
            for pod in self.mixer.make(event.scenario, event.count):
                pod.metadata.creation_timestamp = self.clock()
                self.op.kube_client.create(pod)
                self.report.pods_created += 1
        elif event.kind == TERMINATE:
            bound = self._bound_pods()
            if bound:
                victim = bound[int(self._target_rng.integers(len(bound)))]
                self.op.kube_client.delete(
                    "Pod", victim.metadata.namespace, victim.metadata.name
                )
                self.report.pods_terminated += 1
        elif event.kind == RESIZE:
            bound = self._bound_pods()
            if bound:
                victim = bound[int(self._target_rng.integers(len(bound)))]
                self.op.kube_client.delete(
                    "Pod", victim.metadata.namespace, victim.metadata.name
                )
                replacement = make_pod(
                    name=f"{victim.metadata.name}-r",
                    labels=dict(victim.metadata.labels),
                    requests={
                        "cpu": str(CPU_STEPS[int(self._target_rng.integers(len(CPU_STEPS)))]),
                        "memory": "512Mi",
                    },
                )
                replacement.metadata.creation_timestamp = self.clock()
                self.op.kube_client.create(replacement)
                self.report.pods_terminated += 1
                self.report.pods_created += 1
                self.report.resizes += 1

    # -- sampling ----------------------------------------------------------

    def _sample(self) -> None:
        depth = PENDING_PODS.get()
        if depth is not None:
            self._pending_samples.append(depth)
        for span in TRACER.spans_since(self._trace_mark):
            if span.name == _PRESCREEN_SPAN:
                # cold dispatches pay a one-time XLA compile; the churn
                # medians compare STEADY-STATE device time, so they go in
                # their own bucket (still counted, reported separately)
                mode = str(span.attrs.get("mode", "full"))
                if span.attrs.get("cold"):
                    mode += "_cold"
                self._prescreen_ms.setdefault(mode, []).append(span.duration_ms)
            elif span.name == "solver.phase.device":
                self._device_ms.append(span.duration_ms)
        self._trace_mark = TRACER.mark()

    def _unbound(self) -> int:
        return len(
            self.op.kube_client.list(
                "Pod", field_filter=lambda p: not p.spec.node_name
            )
        )

    # -- runs --------------------------------------------------------------

    def _baselines(self) -> dict:
        return {
            # merged across the per-tenant series the attribution plane
            # splits binds into (ISSUE 16): the soak SLO is whole-stream
            "admission": ADMISSION_TO_BIND.merged_snapshot(),
            "inc": {
                o: INCREMENTAL_SCREEN_TOTAL.get({"outcome": o})
                for o in INC_OUTCOMES
            },
            "chaos": sum(CHAOS_INJECTED_TOTAL.values.values()),
            "machines": len(self.op.kube_client.list("Machine")),
        }

    def _finish(self, base: dict, started_monotonic: Optional[float],
                virtual_elapsed: Optional[float] = None) -> SoakReport:
        self._sample()
        r = self.report
        r.duration_s = (
            virtual_elapsed
            if virtual_elapsed is not None
            else time.monotonic() - started_monotonic
        )
        r.unbound_at_end = self._unbound()
        r.machines_launched = (
            len(self.op.kube_client.list("Machine")) - base["machines"]
        )
        r.admission_count = (
            ADMISSION_TO_BIND.merged_snapshot()[1] - base["admission"][1]
        )
        r.admission_p50_s = ADMISSION_TO_BIND.merged_percentile(0.5, baseline=base["admission"])
        r.admission_p99_s = ADMISSION_TO_BIND.merged_percentile(0.99, baseline=base["admission"])
        if self._pending_samples:
            r.pending_max = max(self._pending_samples)
            r.pending_mean = statistics.fmean(self._pending_samples)
        r.inc_outcomes = {
            o: int(INCREMENTAL_SCREEN_TOTAL.get({"outcome": o}) - base["inc"][o])
            for o in INC_OUTCOMES
        }
        total = sum(r.inc_outcomes.values())
        if total:
            r.resolve_ratio = r.inc_outcomes.get("refresh", 0) / total
        if self._prescreen_ms.get("refresh"):
            r.prescreen_refresh_med_ms = round(
                statistics.median(self._prescreen_ms["refresh"]), 1
            )
        if self._prescreen_ms.get("full"):
            r.prescreen_full_med_ms = round(
                statistics.median(self._prescreen_ms["full"]), 1
            )
        r.prescreen_cold = sum(
            len(v) for k, v in self._prescreen_ms.items() if k.endswith("_cold")
        )
        if self._device_ms:
            r.device_med_ms = round(statistics.median(self._device_ms), 1)
        r.chaos_injected = int(
            sum(CHAOS_INJECTED_TOTAL.values.values()) - base["chaos"]
        )
        return r

    def run(self, on_progress=None) -> SoakReport:
        """Realtime soak: background operator, wall-clock pacing. The event
        schedule's `at` offsets are honored best-effort (a slow solve delays
        later events rather than dropping them — queueing is the signal the
        pending-depth SLO exists to catch)."""
        self._seed_cluster()
        base = self._baselines()
        self._trace_mark = TRACER.mark()
        self.op.start()
        t0 = time.monotonic()
        next_sample = 0.0
        try:
            for event in self.generator.events():
                while True:
                    now = time.monotonic() - t0
                    if now >= next_sample:
                        self._sample()
                        if on_progress is not None:
                            on_progress(now, self.report)
                        next_sample = now + 0.25
                    self.drain_binds()
                    dt = event.at - now
                    if dt <= 0:
                        break
                    time.sleep(min(dt, 0.05))
                self.apply_event(event)
            # tail: let the loop place + bind what the schedule left behind
            deadline = time.monotonic() + self.tail_timeout_s
            while time.monotonic() < deadline:
                self.drain_binds()
                self._sample()
                if self._unbound() == 0 and not self._bind_q:
                    break
                time.sleep(0.05)
            self.report.loops_alive = all(t.is_alive() for t in self.op._threads)
        finally:
            self.op.stop()
        return self._finish(base, t0)

    def run_steps(self) -> SoakReport:
        """Virtual-time soak: FakeClock advanced event-to-event, one
        synchronous op.step() per distinct event time. Deterministic —
        the test-suite harness (and the parity suite's churn source)."""
        clock = self.clock
        if not hasattr(clock, "advance"):
            raise TypeError("run_steps needs a steppable clock (testing.FakeClock)")
        self._seed_cluster()
        base = self._baselines()
        self._trace_mark = TRACER.mark()
        events = self.generator.events()
        virtual = 0.0
        i = 0
        while i < len(events):
            at = events[i].at
            clock.advance(at - virtual)
            virtual = at
            while i < len(events) and events[i].at == at:
                self.apply_event(events[i])
                i += 1
            self.op.step()
            self.drain_binds()
            self._sample()
        # tail: steps until everything bound (bounded — each pass both
        # nominates and, via drain, binds)
        for _ in range(10):
            if self._unbound() == 0 and not self._bind_q:
                break
            clock.advance(1.0)
            virtual += 1.0
            self.op.step()
            self.drain_binds()
        return self._finish(base, None, virtual_elapsed=max(virtual, 1e-9))
