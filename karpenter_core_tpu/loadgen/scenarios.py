"""Scenario pod builders over a BOUNDED vocabulary.

The solver's compiled-program key embeds the label dictionary and the
pow2-bucketed axis widths, and the incremental delta re-solve only replays
when consecutive solves land on the SAME key — so churn pods draw every
label key/value, request size, and constraint shape from small fixed pools.
A generator that minted fresh label values per pod would make every batch a
full re-encode (and a recompile), which is a different benchmark.

Scenario families mirror the fuzz geometries the parity suites cover
(tests/test_differential_fuzz*.py):

  generic  independent pods: app label + stepped cpu/memory requests
  bulk     one deployment-shaped replica group (shared class: exercises
           encode's class dedup + the pack kernel's bulk commits)
  spread   hostname topology spread over a shared app (skew counters)
  anti     required hostname anti-affinity on a dedicated app pool (the
           per-pod item expansion path)
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from karpenter_core_tpu.kube.objects import (
    LabelSelector,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.api.labels import TENANT_LABEL_KEY
from karpenter_core_tpu.testing import make_pod

HOSTNAME_KEY = "kubernetes.io/hostname"

APPS = tuple(f"churn-app-{i}" for i in range(8))
# tenants the churn bills its load to (ISSUE 16): a SMALL FIXED pool for
# the same reason as APPS — the tenant label rides in the pod label dict,
# so fresh tenant values per pod would churn the compiled-program keys.
# The pool also stays under the cardinality guard's slot cap so loadgen
# runs never exercise the "other" overflow by accident.
TENANT_POOL = ("tenant-blue", "tenant-green", "tenant-red")
# ONE spread pool and ONE anti pool, not several: every distinct multiset
# of topology/anti-affinity groups in a batch is a STATIC parameter of the
# compiled pack kernel (the geometry key's topology signature), so pools
# multiply the program population combinatorially — the bounded-vocabulary
# rule applies to constraint GROUPS exactly as it does to label values
SPREAD_APPS = ("churn-spread-0",)
ANTI_APPS = ("churn-anti-0",)
CPU_STEPS = (0.25, 0.5, 1.0, 1.5)
MEM_STEPS = ("256Mi", "512Mi", "1Gi")


class ScenarioMixer:
    """Builds scenario pods deterministically from a seeded rng; pod names
    are unique per mixer instance (one mixer per soak run)."""

    def __init__(self, rng: np.random.Generator):
        self.rng = rng
        self._n = 0
        self._groups = 0

    def _name(self, scenario: str) -> str:
        self._n += 1
        return f"{scenario}-{self._n}"

    def _tenant(self) -> str:
        """One tenant per scenario GROUP, round-robin off a plain counter:
        deterministic and rng-stream-neutral (pre-tenant replays draw the
        identical app/request sequences), and group-level — a bulk
        deployment stays one encode class instead of splitting per pod."""
        tenant = TENANT_POOL[self._groups % len(TENANT_POOL)]
        self._groups += 1
        return tenant

    def _requests(self) -> Dict[str, str]:
        return {
            "cpu": str(CPU_STEPS[int(self.rng.integers(len(CPU_STEPS)))]),
            "memory": MEM_STEPS[int(self.rng.integers(len(MEM_STEPS)))],
        }

    def generic(self, count: int) -> List:
        tenant = self._tenant()
        return [
            make_pod(
                name=self._name("generic"),
                labels={
                    "app": APPS[int(self.rng.integers(len(APPS)))],
                    TENANT_LABEL_KEY: tenant,
                },
                requests=self._requests(),
            )
            for _ in range(count)
        ]

    def bulk(self, count: int) -> List:
        app = APPS[int(self.rng.integers(len(APPS)))]
        requests = self._requests()
        labels = {"app": app, TENANT_LABEL_KEY: self._tenant()}
        return [
            make_pod(name=self._name("bulk"), labels=dict(labels), requests=requests)
            for _ in range(count)
        ]

    def spread(self, count: int) -> List:
        app = SPREAD_APPS[int(self.rng.integers(len(SPREAD_APPS)))]
        tenant = self._tenant()
        requests = self._requests()
        constraint = TopologySpreadConstraint(
            max_skew=2,
            topology_key=HOSTNAME_KEY,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels={"app": app}),
        )
        return [
            make_pod(
                name=self._name("spread"),
                labels={"app": app, TENANT_LABEL_KEY: tenant},
                requests=requests,
                topology_spread=[constraint],
            )
            for _ in range(count)
        ]

    def anti(self, count: int) -> List:
        app = ANTI_APPS[int(self.rng.integers(len(ANTI_APPS)))]
        tenant = self._tenant()
        term = PodAffinityTerm(
            topology_key=HOSTNAME_KEY,
            label_selector=LabelSelector(match_labels={"app": app}),
        )
        return [
            make_pod(
                name=self._name("anti"),
                labels={"app": app, TENANT_LABEL_KEY: tenant},
                requests={"cpu": "0.5"},
                pod_anti_affinity_required=[term],
            )
            for _ in range(count)
        ]

    def make(self, scenario: str, count: int) -> List:
        return SCENARIOS[scenario](self, count)


SCENARIOS: Dict[str, Callable[[ScenarioMixer, int], List]] = {
    "generic": ScenarioMixer.generic,
    "bulk": ScenarioMixer.bulk,
    "spread": ScenarioMixer.spread,
    "anti": ScenarioMixer.anti,
}
