"""Structured logging: logfmt/JSON lines with per-thread bound context,
trace-id correlation, and a bounded in-memory ring served at /debug/logs.

The reference controller logs through zap with key=value context
(logger.WithValues(controller, request)); the repo's ad-hoc stdlib logging
and bare prints gave no correlated trail for a solve gone wrong. This module
is the one logging surface for the package:

  * the DISABLED path is near-zero — same discipline as obs/tracer.py and
    the chaos registry: a log call on a disabled sink is ONE level
    comparison returning immediately, so log sites live permanently on
    production hot paths (kube transport retries, chaos injections,
    circuit-breaker transitions);
  * context BINDS per thread: `with log.bound(controller=..., reconcile=...)`
    stamps every record emitted inside the scope (the WithValues analog),
    and the active obs.tracer trace id is attached automatically so log
    lines join spans — grep one trace id across /debug/logs and
    /debug/trace and you see the same solve;
  * records land in a bounded ring (served by the operator's /debug/logs)
    AND stream to stderr as logfmt or JSON lines, selected by
    KARPENTER_TPU_LOG (e.g. `info`, `debug:json`) — parsed in exactly one
    place, configure_logging_from_env.
"""
from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.obs.envflags import FALSY as _FALSY, TRUTHY as _TRUTHY
from karpenter_core_tpu.obs.tracer import TRACER

DEBUG, INFO, WARNING, ERROR = 10, 20, 30, 40
OFF = 100  # disabled: no named level reaches it

LEVEL_NAMES = {DEBUG: "debug", INFO: "info", WARNING: "warning", ERROR: "error"}
LEVELS = {name: num for num, name in LEVEL_NAMES.items()}
LEVELS["warn"] = WARNING


# ---------------------------------------------------------------------------
# formatting


def _fmt_ts(ts: float) -> str:
    whole = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts))
    return f"{whole}.{int((ts % 1) * 1e3):03d}Z"


def _logfmt_value(value: object) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return f"{value:g}" if isinstance(value, float) else str(value)
    s = str(value)
    if s and not any(c in s for c in ' "=\n\t'):
        return s
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n") + '"'


def format_logfmt(record: Dict[str, object]) -> str:
    """One logfmt line; ts/level/logger/msg lead, then bound+call fields."""
    parts = [f"ts={_fmt_ts(record['ts'])}"]
    for key in ("level", "logger", "msg"):
        parts.append(f"{key}={_logfmt_value(record[key])}")
    for key, value in record.items():
        if key in ("ts", "level", "logger", "msg"):
            continue
        parts.append(f"{key}={_logfmt_value(value)}")
    return " ".join(parts)


def format_json(record: Dict[str, object]) -> str:
    out = dict(record)
    out["ts"] = _fmt_ts(record["ts"])
    return json.dumps(out, default=str, separators=(",", ":"))


# ---------------------------------------------------------------------------
# per-thread bound context


class _Tls(threading.local):
    def __init__(self):
        self.stack: List[Dict[str, object]] = [{}]


_tls = _Tls()


class bound:
    """Context manager stamping every record emitted in-scope (and in
    nested scopes) with the given fields — the WithValues analog. Nests:
    inner scopes merge over outer ones."""

    __slots__ = ("ctx",)

    def __init__(self, **ctx):
        self.ctx = ctx

    def __enter__(self):
        stack = _tls.stack
        stack.append({**stack[-1], **self.ctx})
        return self

    def __exit__(self, exc_type, exc, tb):
        _tls.stack.pop()
        return False


def bound_context() -> Dict[str, object]:
    """The calling thread's current bound fields (read-only view)."""
    return dict(_tls.stack[-1])


# ---------------------------------------------------------------------------
# sink


class LogSink:
    """Level-gated fan-out: bounded in-memory ring + a line stream.

    `level` is the one hot-path gate: Logger methods compare against it
    before building anything, so a disabled sink (level=OFF) costs one
    comparison per call site."""

    def __init__(self, capacity: int = 4096):
        self.level = OFF
        self.fmt = "logfmt"
        self.stream = None  # line sink; None = ring only
        self.capacity = capacity
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._emitted = 0

    @property
    def enabled(self) -> bool:
        return self.level < OFF

    def configure(self, level: int = INFO, fmt: str = "logfmt",
                  stream=...) -> "LogSink":
        # under _mu so concurrent configures (operator boot vs embedder)
        # can't tear fmt/stream across generations; the HOT-PATH reads of
        # these latches stay lock-free by design (one comparison per call
        # site) — audited in racewatch's suppression table (ISSUE 13)
        with self._mu:
            self.level = level
            self.fmt = fmt
            if stream is not ...:
                self.stream = stream
        return self

    def disable(self) -> "LogSink":
        with self._mu:
            self.level = OFF
        return self

    def emit(self, record: Dict[str, object]) -> None:
        with self._mu:
            self._ring.append(record)
            self._emitted += 1
        stream = self.stream
        if stream is None and record.get("level") == "error":
            # last-resort semantics (stdlib logging's lastResort handler):
            # error records from a process that never configured the sink
            # (embedding, one-off scripts) still reach stderr — a crashing
            # watch pump must never be invisible
            stream = sys.stderr
        if stream is not None:
            line = (
                format_json(record) if self.fmt == "json"
                else format_logfmt(record)
            )
            try:
                stream.write(line + "\n")
            except Exception:  # noqa: BLE001 — a dead stream must not break a solve
                pass

    # -- reading (the /debug/logs surface) ---------------------------------

    @property
    def dropped(self) -> int:
        with self._mu:
            return self._emitted - len(self._ring)

    def records(self) -> List[Dict[str, object]]:
        with self._mu:
            return list(self._ring)

    def lines(self, fmt: Optional[str] = None) -> str:
        formatter = format_json if (fmt or self.fmt) == "json" else format_logfmt
        out = [formatter(r) for r in self.records()]
        if self.dropped:
            out.append(f"# dropped={self.dropped} (ring full)")
        return "\n".join(out) + "\n" if out else ""

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()
            self._emitted = 0


SINK = LogSink()


# ---------------------------------------------------------------------------
# loggers


class Logger:
    """Named logger. Every method is gated on SINK.level FIRST — the
    disabled path is one comparison, mirroring TRACER.span()'s contract."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def debug(self, event: str, **fields) -> None:
        if DEBUG >= SINK.level:
            self._emit(DEBUG, event, fields)

    def info(self, event: str, **fields) -> None:
        if INFO >= SINK.level:
            self._emit(INFO, event, fields)

    def warning(self, event: str, **fields) -> None:
        if WARNING >= SINK.level:
            self._emit(WARNING, event, fields)

    def error(self, event: str, **fields) -> None:
        # errors bypass the level gate: an unconfigured sink still rings
        # them and LogSink.emit last-resorts them to stderr (the stdlib
        # lastResort analog) — error paths are cold, the gate is for the
        # hot debug/info sites
        self._emit(ERROR, event, fields)

    def exception(self, event: str, **fields) -> None:
        """error() + the active exception's type/message/stack."""
        exc_type, exc, tb = sys.exc_info()
        if exc_type is not None:
            fields.setdefault("error", exc_type.__name__)
            fields.setdefault("error_detail", str(exc))
            buf = io.StringIO()
            traceback.print_exception(exc_type, exc, tb, file=buf)
            fields.setdefault("stack", buf.getvalue())
        self._emit(ERROR, event, fields)

    def _emit(self, level: int, event: str, fields: Dict[str, object]) -> None:
        record: Dict[str, object] = {
            "ts": time.time(),
            "level": LEVEL_NAMES[level],
            "logger": self.name,
            "msg": event,
        }
        ctx = _tls.stack[-1]
        if ctx:
            record.update(ctx)
        # span correlation: log lines inside an active span carry its trace
        # id so /debug/logs joins /debug/trace on one key
        if TRACER.enabled:
            trace_id = TRACER.current_trace_id()
            if trace_id is not None:
                record["trace_id"] = trace_id
        if fields:
            record.update(fields)
        SINK.emit(record)


_loggers: Dict[str, Logger] = {}
_loggers_mu = threading.Lock()


def get_logger(name: str) -> Logger:
    with _loggers_mu:
        logger = _loggers.get(name)
        if logger is None:
            logger = _loggers[name] = Logger(name)
        return logger


# ---------------------------------------------------------------------------
# KARPENTER_TPU_LOG


def parse_log_spec(raw: str) -> Optional[tuple]:
    """`level[:format]` -> (level, fmt), None for off/unset. Truthy
    spellings mean info; unknown levels fall back to info so a typo'd spec
    still logs rather than silently disabling."""
    raw = raw.strip().lower()
    if not raw or raw in _FALSY:
        return None
    level_part, _, fmt_part = raw.partition(":")
    if level_part in ("json", "logfmt"):  # bare format: `KARPENTER_TPU_LOG=json`
        level_part, fmt_part = "info", level_part
    if level_part in _TRUTHY:
        level_part = "info"
    level = LEVELS.get(level_part, INFO)
    fmt = "json" if fmt_part == "json" else "logfmt"
    return level, fmt


def configure_logging_from_env(default_level: str = "") -> bool:
    """Arm/disarm SINK from KARPENTER_TPU_LOG — the ONE parser of that
    variable, shared by the import-time hook (default off) and the
    operator / solver-service entrypoints (default info). Returns the
    resulting enabled state."""
    spec = parse_log_spec(
        envflags.raw("KARPENTER_TPU_LOG") or default_level
    )
    if spec is None:
        SINK.disable()
    else:
        level, fmt = spec
        SINK.configure(level=level, fmt=fmt, stream=sys.stderr)
    return SINK.enabled


# KARPENTER_TPU_LOG set arms logging at import, so any entrypoint (bench,
# tests, one-off scripts) opts in uniformly — same hook as KARPENTER_TPU_TRACE
configure_logging_from_env()
