"""Solve-path tracing: nested spans, a bounded ring buffer, Chrome
trace-event export (loadable in Perfetto / chrome://tracing), and a metrics
bridge into the in-process registry.

Design constraints (the reasons this is not an OpenTelemetry dependency):

  * the disabled path must be near-zero — Tracer.span()/add_span() on a
    disabled tracer is ONE attribute check returning a shared no-op object,
    no allocation — so the instrumentation lives permanently on the
    production hot path (provisioner reconcile -> batcher window ->
    Scheduler.Solve -> TPUSolver phases -> gRPC service -> bind);
  * spans must be recordable retroactively (add_span with explicit
    timestamps) because the solver's phase boundaries are sequential marks
    inside one function, not lexically nested blocks;
  * everything is process-local and thread-safe: solver phases run on the
    reconcile thread, machine launches fan out over a pool, and the gRPC
    server handles calls on its own executor.

The analog in the JAX ecosystem is jax.profiler's Perfetto workflow for
DEVICE time; this tracer covers the host-side pipeline around it and the
two compose (device_profiler below wraps device solves in jax.profiler when
KARPENTER_TPU_PROFILE points at a directory).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Dict, List, Optional

from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY

# -- instruments fed by the span bridge (names chartered in ISSUE 1) --------

SOLVER_PHASE_DURATION = REGISTRY.histogram(
    f"{NAMESPACE}_solver_phase_duration_seconds",
    "Duration of each TPU solver phase (encode/args/pack/upload/device/"
    "fetch/bind), fed by solver.phase.* spans",
)
SOLVER_SOLVE_DURATION = REGISTRY.histogram(
    f"{NAMESPACE}_solver_solve_duration_seconds",
    "End-to-end Solve() duration including relaxation rounds",
)
SOLVER_BATCH_SIZE = REGISTRY.gauge(
    f"{NAMESPACE}_solver_batch_size",
    "Pod count of the most recent Solve() batch",
)

_PHASE_PREFIX = "solver.phase."

# gRPC metadata key carrying the trace id across the solver-service
# boundary (client stub attaches, server handler adopts)
TRACE_HEADER = "x-karpenter-trace-id"


def _bridge(span: "Span") -> None:
    """Span completion -> metrics registry. Called with the tracer enabled
    only; controller reconcile histograms are observed at their own sites
    (operator/controller.py) so they are never double-counted here."""
    name = span.name
    if name.startswith(_PHASE_PREFIX):
        SOLVER_PHASE_DURATION.observe(
            span.duration_s, {"phase": name[len(_PHASE_PREFIX):]}
        )
    elif name == "solver.solve":
        # deprovisioning simulations re-enter the same solver: keep their
        # solves out of the provisioning-latency series (context label /
        # batch-size gauge) or consolidation-heavy clusters would report
        # simulation numbers as provisioning SLO data
        ctx = str(span.attrs.get("context", "provisioning"))
        SOLVER_SOLVE_DURATION.observe(span.duration_s, {"context": ctx})
        pods = span.attrs.get("pods")
        if pods is not None and ctx == "provisioning":
            SOLVER_BATCH_SIZE.set(float(pods))


# ---------------------------------------------------------------------------


class Span:
    """One finished (or live) span. Timestamps are perf_counter_ns."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attrs", "tid", "_tracer",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 attrs: Dict[str, object]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.tid = threading.get_ident()
        self.start_ns = 0
        self.end_ns = 0

    @property
    def duration_s(self) -> float:
        return (self.end_ns - self.start_ns) / 1e9

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def set(self, **attrs) -> "Span":
        """Attach attributes to a live span (e.g. rounds known at exit)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._pop(self)
        return False


class _NoopSpan:
    """Shared do-nothing span for the disabled path: span() returns THIS
    object without allocating, so a disabled tracer costs one flag check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Thread-safe tracer with a bounded ring-buffer span store.

    Spans nest via a thread-local stack: a span opened while another is
    live on the same thread becomes its child and inherits its trace id.
    Roots mint a fresh trace id unless one is passed explicitly (the gRPC
    server passes the client's propagated id). Finished spans land in a
    deque(maxlen=capacity); `dropped` counts ring-buffer evictions so
    truncation is always visible in exports.
    """

    def __init__(self, capacity: int = 65536):
        self.enabled = False
        self.capacity = capacity
        self._mu = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._finished = 0  # total spans ever recorded (monotonic)
        self._ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._tls = threading.local()
        self._t0_ns = time.perf_counter_ns()
        self._pid = os.getpid()

    # -- lifecycle ---------------------------------------------------------

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()
            self._finished = 0

    # -- recording ---------------------------------------------------------

    def span(self, name: str, trace_id: Optional[str] = None, **attrs):
        """Context manager for a live span. Disabled -> shared no-op."""
        if not self.enabled:
            return NOOP_SPAN
        return self._make(name, trace_id, attrs)

    def add_span(self, name: str, start_ns: int, end_ns: int,
                 trace_id: Optional[str] = None, **attrs) -> None:
        """Record an already-finished region (phase marks inside one
        function body); parented to the calling thread's current span."""
        if not self.enabled:
            return
        span = self._make(name, trace_id, attrs)
        span.start_ns = start_ns
        span.end_ns = end_ns
        self._record(span)

    def _make(self, name, trace_id, attrs) -> Span:
        parent = self._current()
        if trace_id is None:
            trace_id = (
                parent.trace_id if parent is not None
                else f"t{next(self._trace_ids):08x}"
            )
        return Span(
            self, name, trace_id, next(self._ids),
            parent.span_id if parent is not None else None, attrs,
        )

    # -- nesting (thread-local stack) --------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def current_trace_id(self) -> Optional[str]:
        """Trace id of the calling thread's active span (propagation)."""
        cur = self._current()
        return cur.trace_id if cur is not None else None

    def current_span_name(self) -> Optional[str]:
        """Name of the calling thread's active span (e.g. to tell a
        provisioning solve from a deprovisioning-simulation solve)."""
        cur = self._current()
        return cur.name if cur is not None else None

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # mispaired exit: drop it and everything above
            del stack[stack.index(span):]
        self._record(span)

    def _record(self, span: Span) -> None:
        with self._mu:
            self._spans.append(span)
            self._finished += 1
        try:
            _bridge(span)
        except Exception:  # noqa: BLE001 — metrics must never break a solve
            pass

    # -- reading -----------------------------------------------------------

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring buffer (truncation accounting)."""
        with self._mu:
            return self._finished - len(self._spans)

    def mark(self) -> int:
        """Sequence checkpoint; pass to spans_since()/phase_ms_since()."""
        with self._mu:
            return self._finished

    def spans(self) -> List[Span]:
        with self._mu:
            return list(self._spans)

    def spans_since(self, seq: int) -> List[Span]:
        """Spans recorded after mark() returned `seq` (ring-aware: spans
        evicted since the mark are simply gone from the result)."""
        with self._mu:
            newer = self._finished - seq
            if newer <= 0:
                return []
            return list(self._spans)[-min(newer, len(self._spans)):]

    def phase_ms_since(self, seq: int, prefix: str = _PHASE_PREFIX,
                       last_only: bool = False) -> Dict[str, float]:
        """Per-phase milliseconds for solver.phase.* spans recorded after
        `seq` — the bench's phase-breakdown source. Default sums every
        occurrence (all relaxation rounds); last_only=True keeps only the
        final occurrence per phase, matching the historical
        last-round-overwrite timers so old bench artifacts stay comparable."""
        out: Dict[str, float] = {}
        for span in self.spans_since(seq):
            if span.name.startswith(prefix):
                key = span.name[len(prefix):]
                prev = 0.0 if last_only else out.get(key, 0.0)
                out[key] = round(prev + span.duration_ms, 1)
        return out

    # -- export ------------------------------------------------------------

    def chrome_trace(self) -> Dict[str, object]:
        """Chrome trace-event JSON (dict): complete ('X') events with
        microsecond ts/dur, loadable in Perfetto and chrome://tracing."""
        events = []
        for span in self.spans():
            args = {"trace_id": span.trace_id, "span_id": span.span_id}
            if span.parent_id is not None:
                args["parent_id"] = span.parent_id
            for k, v in span.attrs.items():
                args[k] = v if isinstance(v, (int, float, bool)) else str(v)
            events.append(
                {
                    "name": span.name,
                    "cat": "karpenter",
                    "ph": "X",
                    "ts": (span.start_ns - self._t0_ns) / 1e3,
                    "dur": max(span.end_ns - span.start_ns, 0) / 1e3,
                    "pid": self._pid,
                    "tid": span.tid % 2**31,  # chrome wants a small int
                    "args": args,
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": self.dropped},
        }

    def export_chrome_trace(self, path: str) -> str:
        # write-temp + atomic rename: a Perfetto/chrome tab polling the
        # trace file mid-export must never load a JSON prefix
        # (atomic-write rule, ISSUE 13)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def summary(self) -> str:
        """Compact per-span-name text summary (count / total / mean / max)."""
        agg: Dict[str, List[float]] = {}
        for span in self.spans():
            agg.setdefault(span.name, []).append(span.duration_ms)
        lines = [
            f"{'span':<40} {'count':>6} {'total_ms':>10} {'mean_ms':>9} {'max_ms':>9}"
        ]
        for name in sorted(agg):
            ds = agg[name]
            lines.append(
                f"{name:<40} {len(ds):>6} {sum(ds):>10.1f} "
                f"{sum(ds) / len(ds):>9.1f} {max(ds):>9.1f}"
            )
        if self.dropped:
            lines.append(f"(dropped {self.dropped} spans: ring buffer full)")
        return "\n".join(lines)


# the process-wide tracer
TRACER = Tracer()

from karpenter_core_tpu.obs import envflags  # noqa: E402
from karpenter_core_tpu.obs.envflags import FALSY as _FALSY, TRUTHY as _TRUTHY  # noqa: E402


def enable_tracing_from_env(default_on: bool = False) -> bool:
    """Arm/disarm TRACER from KARPENTER_TPU_TRACE — the ONE parser of that
    variable, shared by the import-time hook (default off) and the
    operator / solver-service entrypoints (default on), so truthy
    spellings like 'true'/'on' behave identically everywhere. Returns the
    resulting enabled state."""
    raw = envflags.raw("KARPENTER_TPU_TRACE").strip().lower()
    if raw in _FALSY:
        TRACER.disable()
    elif default_on or raw in _TRUTHY:
        TRACER.enable()
    return TRACER.enabled


# KARPENTER_TPU_TRACE set truthy arms tracing at import, so any entrypoint
# (bench, tests, one-off scripts) opts in uniformly
enable_tracing_from_env(default_on=False)


def profile_dir() -> str:
    """The device-profiling output directory, "" when profiling is off.
    The ONE place the KARPENTER_TPU_PROFILE / KARPENTER_JAX_TRACE_DIR
    (pre-ISSUE-1 spelling) env vars are interpreted — callers that need to
    know whether profiling is active (e.g. to barrier the dispatch) must
    use this instead of re-reading the env."""
    return (
        envflags.raw("KARPENTER_TPU_PROFILE")
        or envflags.raw("KARPENTER_JAX_TRACE_DIR")
    )


def device_profiler():
    """Context manager wrapping a device solve in jax.profiler when
    profile_dir() names a directory; no-op otherwise or when the profiler
    is unavailable. The captured trace is the device-side complement of
    this module's host spans (view with tensorboard/xprof)."""
    trace_dir = profile_dir()
    if trace_dir:
        try:
            import jax

            return jax.profiler.trace(trace_dir)
        except Exception:  # noqa: BLE001 — profiling is opt-in, never fatal
            pass
    return nullcontext()
