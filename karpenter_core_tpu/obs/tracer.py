"""Solve-path tracing: nested spans, a bounded ring buffer, Chrome
trace-event export (loadable in Perfetto / chrome://tracing), and a metrics
bridge into the in-process registry.

Design constraints (the reasons this is not an OpenTelemetry dependency):

  * the disabled path must be near-zero — Tracer.span()/add_span() on a
    disabled tracer is ONE attribute check returning a shared no-op object,
    no allocation — so the instrumentation lives permanently on the
    production hot path (provisioner reconcile -> batcher window ->
    Scheduler.Solve -> TPUSolver phases -> gRPC service -> bind);
  * spans must be recordable retroactively (add_span with explicit
    timestamps) because the solver's phase boundaries are sequential marks
    inside one function, not lexically nested blocks;
  * everything is process-local and thread-safe: solver phases run on the
    reconcile thread, machine launches fan out over a pool, and the gRPC
    server handles calls on its own executor.

The analog in the JAX ecosystem is jax.profiler's Perfetto workflow for
DEVICE time; this tracer covers the host-side pipeline around it and the
two compose (device_profiler below wraps device solves in jax.profiler when
KARPENTER_TPU_PROFILE points at a directory).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs import reqctx

# -- instruments fed by the span bridge (names chartered in ISSUE 1) --------

SOLVER_PHASE_DURATION = REGISTRY.histogram(
    f"{NAMESPACE}_solver_phase_duration_seconds",
    "Duration of each TPU solver phase (encode/args/pack/upload/device/"
    "fetch/bind), fed by solver.phase.* spans",
)
SOLVER_SOLVE_DURATION = REGISTRY.histogram(
    f"{NAMESPACE}_solver_solve_duration_seconds",
    "End-to-end Solve() duration including relaxation rounds",
)
SOLVER_BATCH_SIZE = REGISTRY.gauge(
    f"{NAMESPACE}_solver_batch_size",
    "Pod count of the most recent Solve() batch",
)

_PHASE_PREFIX = "solver.phase."

# gRPC metadata key carrying the trace id across the solver-service
# boundary (client stub attaches, server handler adopts)
TRACE_HEADER = "x-karpenter-trace-id"


def _bridge(span: "Span") -> None:
    """Span completion -> metrics registry. Called with the tracer enabled
    only; controller reconcile histograms are observed at their own sites
    (operator/controller.py) so they are never double-counted here.
    GRAFTED spans (a child process's, folded in over the frame protocol)
    never pass through: the child already observed its own instruments,
    which reach the parent exposition via the metrics merge — bridging
    the grafted copy would double-count every phase (ISSUE 15)."""
    name = span.name
    # the span's tenant attr (stamped by span() from the bound request
    # context) fans the phase/solve histograms out per tenant — through the
    # cardinality guard, so a label flood collapses into "other"
    tenant = span.attrs.get("tenant")
    if name.startswith(_PHASE_PREFIX):
        labels = {"phase": name[len(_PHASE_PREFIX):]}
        if tenant is not None:
            labels["tenant"] = reqctx.TENANTS.admit(str(tenant))
        SOLVER_PHASE_DURATION.observe(span.duration_s, labels)
    elif name == "solver.solve":
        # deprovisioning simulations re-enter the same solver: keep their
        # solves out of the provisioning-latency series (context label /
        # batch-size gauge) or consolidation-heavy clusters would report
        # simulation numbers as provisioning SLO data
        ctx = str(span.attrs.get("context", "provisioning"))
        labels = {"context": ctx}
        if tenant is not None:
            labels["tenant"] = reqctx.TENANTS.admit(str(tenant))
        SOLVER_SOLVE_DURATION.observe(
            span.duration_s, labels,
            # the exemplar links a bad latency bucket to its trace — and,
            # through the trace id, to the flight record of the same solve
            exemplar={"trace_id": span.trace_id} if span.trace_id else None,
        )
        pods = span.attrs.get("pods")
        if pods is not None and ctx == "provisioning":
            SOLVER_BATCH_SIZE.set(float(pods))


# ---------------------------------------------------------------------------


class Span:
    """One finished (or live) span. Timestamps are perf_counter_ns."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attrs", "tid", "_tracer",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 attrs: Dict[str, object]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.tid = threading.get_ident()
        self.start_ns = 0
        self.end_ns = 0

    @property
    def duration_s(self) -> float:
        return (self.end_ns - self.start_ns) / 1e9

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def set(self, **attrs) -> "Span":
        """Attach attributes to a live span (e.g. rounds known at exit)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._pop(self)
        return False


class _NoopSpan:
    """Shared do-nothing span for the disabled path: span() returns THIS
    object without allocating, so a disabled tracer costs one flag check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Thread-safe tracer with a bounded ring-buffer span store.

    Spans nest via a thread-local stack: a span opened while another is
    live on the same thread becomes its child and inherits its trace id.
    Roots mint a fresh trace id unless one is passed explicitly (the gRPC
    server passes the client's propagated id). Finished spans land in a
    deque(maxlen=capacity); `dropped` counts ring-buffer evictions so
    truncation is always visible in exports.
    """

    # per-graft span budget (satellite, ISSUE 15): a chatty child can never
    # push more than this many spans into the parent ring per exchange —
    # the frame side mirrors the cap at export (MAX_EXPORT_SPANS/BYTES)
    MAX_GRAFT_SPANS = 256

    def __init__(self, capacity: int = 65536):
        self.enabled = False
        self.capacity = capacity
        self._mu = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._finished = 0  # total spans ever recorded (monotonic)
        self._ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._tls = threading.local()
        self._t0_ns = time.perf_counter_ns()
        self._pid = os.getpid()
        # graft accounting (ISSUE 15): spans a child exported but this
        # tracer refused (per-graft cap) PLUS spans the child itself
        # dropped at export — truncation is always visible, like `dropped`
        self._graft_dropped = 0
        self._grafted = 0
        # span spill (killed-child salvage): when set, finished spans with
        # a spilled prefix are mirrored into a small ring + atomically
        # rewritten to `spill_path` so the PARENT can salvage a killed
        # child's last phases from disk. None (the default) costs one
        # attribute check per recorded span, zero when tracing is off.
        self._spill_path: Optional[str] = None
        self._spill_prefix: Tuple[str, ...] = ()
        self._spill_ring: deque = deque(maxlen=64)

    # -- lifecycle ---------------------------------------------------------

    def enable(self) -> "Tracer":
        # the write latches under _mu; the hot-path `enabled` read stays
        # lock-free by contract (racewatch suppression table, ISSUE 13 —
        # same posture as FlightRecorder.enabled)
        with self._mu:
            self.enabled = True
        return self

    def disable(self) -> "Tracer":
        with self._mu:
            self.enabled = False
        return self

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()
            self._finished = 0

    # -- recording ---------------------------------------------------------

    def span(self, name: str, trace_id: Optional[str] = None, **attrs):
        """Context manager for a live span. Disabled -> shared no-op."""
        if not self.enabled:
            return NOOP_SPAN
        return self._make(name, trace_id, attrs)

    def add_span(self, name: str, start_ns: int, end_ns: int,
                 trace_id: Optional[str] = None, **attrs) -> None:
        """Record an already-finished region (phase marks inside one
        function body); parented to the calling thread's current span."""
        if not self.enabled:
            return
        span = self._make(name, trace_id, attrs)
        span.start_ns = start_ns
        span.end_ns = end_ns
        self._record(span)

    def instant(self, name: str, trace_id: Optional[str] = None,
                **attrs) -> None:
        """Record a zero-duration INSTANT event (kill, respawn, breaker
        transition, wedge verdict) — rendered as a Perfetto instant ('i')
        marker instead of a duration slice. Disabled -> one flag check."""
        if not self.enabled:
            return
        now = time.perf_counter_ns()
        attrs["instant"] = True
        self.add_span(name, now, now, trace_id=trace_id, **attrs)

    def _make(self, name, trace_id, attrs) -> Span:
        # a bound request context stamps its tenant onto every locally
        # created span (the raw tenant, not the guarded label: span attrs
        # are not metric labels — the _bridge routes through the guard
        # before labeling). Grafted spans keep whatever the child stamped.
        if "tenant" not in attrs:
            tenant = reqctx.current_tenant()
            if tenant is not None:
                attrs["tenant"] = tenant
        parent = self._current()
        if trace_id is None:
            trace_id = (
                parent.trace_id if parent is not None
                else f"t{next(self._trace_ids):08x}"
            )
        return Span(
            self, name, trace_id, next(self._ids),
            parent.span_id if parent is not None else None, attrs,
        )

    # -- nesting (thread-local stack) --------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def current_trace_id(self) -> Optional[str]:
        """Trace id of the calling thread's active span (propagation)."""
        cur = self._current()
        return cur.trace_id if cur is not None else None

    def current_span_name(self) -> Optional[str]:
        """Name of the calling thread's active span (e.g. to tell a
        provisioning solve from a deprovisioning-simulation solve)."""
        cur = self._current()
        return cur.name if cur is not None else None

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # mispaired exit: drop it and everything above
            del stack[stack.index(span):]
        self._record(span)

    def _record(self, span: Span) -> None:
        with self._mu:
            self._spans.append(span)
            self._finished += 1
        try:
            _bridge(span)
        except Exception:  # noqa: BLE001 — metrics must never break a solve
            pass
        if self._spill_path is not None and span.name.startswith(
            self._spill_prefix
        ):
            self._spill(span)

    # -- killed-child salvage spill (ISSUE 15) ------------------------------

    def set_spill(self, path: Optional[str],
                  prefixes: Tuple[str, ...] = ("solver.",)) -> None:
        """Arm (path) / disarm (None) the span spill: finished spans whose
        name starts with one of `prefixes` are mirrored to `path` as an
        export payload, atomically rewritten per span. The solver-host
        CHILD arms this beside its heartbeat file so the parent can graft
        the last phases of a dispatch that never got to answer (the child
        was SIGKILLed mid-solve)."""
        with self._mu:
            self._spill_ring.clear()
            self._spill_prefix = tuple(prefixes)
            self._spill_path = path

    def reset_spill(self) -> None:
        """Clear the spill ring + file. The solver-host child calls this
        at each dispatch start so a later kill's salvage never re-grafts
        spans already delivered in an earlier response frame."""
        with self._mu:
            self._spill_ring.clear()
            path = self._spill_path
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _spill(self, span: Span) -> None:
        try:
            from karpenter_core_tpu.utils import supervise

            with self._mu:
                self._spill_ring.append(span)
                payload = export_spans(list(self._spill_ring))
                path = self._spill_path
            if path is not None:
                supervise.atomic_write_json(path, payload)
        except Exception:  # noqa: BLE001 — salvage is best-effort by design
            pass

    # -- reading -----------------------------------------------------------

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring buffer (truncation accounting)."""
        with self._mu:
            return self._finished - len(self._spans)

    @property
    def graft_dropped(self) -> int:
        """Child-exported spans NOT grafted (per-graft cap here + export
        cap on the frame side) — the cap-and-count contract's counter."""
        with self._mu:
            return self._graft_dropped

    @property
    def grafted(self) -> int:
        with self._mu:
            return self._grafted

    # -- cross-process graft (ISSUE 15 tentpole) ----------------------------

    def graft(self, payload: Optional[Dict[str, object]], *,
              pid: Optional[int] = None, generation: Optional[int] = None,
              trace_id: Optional[str] = None,
              **extra_attrs) -> int:
        """Fold a child process's exported span delta (`export_spans`
        payload, off the solver-host response/stats frame or a salvage
        spill file) into this tracer's ring, parented under the calling
        thread's CURRENT span (`solver.host.request` on the dispatch path).

        Contract:

          * timestamps rebase onto this process's perf_counter clock via
            the payload's `now_ns` anchor (skew = one pipe hop — fine for
            a timeline; never used for arithmetic beyond display);
          * child span/parent ids are REMAPPED to fresh parent ids with
            the child's internal structure preserved; orphans (parent not
            in the payload) re-home under the current span;
          * every grafted span is tagged {pid, generation} (+extra_attrs)
            and re-homed onto the graft trace id, so /debug/trace,
            flightrec.phases_ms and the bench phase breakdown see the
            child's solver.phase.* spans as part of the ONE solve;
          * bounded: at most MAX_GRAFT_SPANS per call land in the ring
            (which is itself the bounded deque — grafts can never grow it
            past capacity); refused + child-side-dropped spans count in
            `graft_dropped`;
          * grafted spans NEVER re-enter the metrics bridge (the child
            already observed its instruments; they arrive via the metrics
            merge instead).

        Returns the number of spans grafted."""
        if not self.enabled or not payload:
            return 0
        entries = list(payload.get("spans") or ())
        child_dropped = int(payload.get("dropped", 0) or 0)
        refused = max(0, len(entries) - self.MAX_GRAFT_SPANS)
        if refused:
            # keep the NEWEST spans: the tail names the phase closest to
            # the outcome (or the kill)
            entries = entries[-self.MAX_GRAFT_SPANS:]
        parent = self._current()
        if trace_id is None:
            trace_id = (
                parent.trace_id if parent is not None
                else f"t{next(self._trace_ids):08x}"
            )
        now_ns = payload.get("now_ns")
        offset = (
            time.perf_counter_ns() - int(now_ns)
            if isinstance(now_ns, (int, float)) and now_ns else 0
        )
        if pid is None:
            p = payload.get("pid")
            pid = int(p) if isinstance(p, (int, float)) else None
        id_map: Dict[int, int] = {}
        for entry in entries:
            old = entry.get("i")
            if isinstance(old, int):
                id_map[old] = next(self._ids)
        grafted: List[Span] = []
        for entry in entries:
            try:
                attrs = dict(entry.get("a") or {})
                if pid is not None:
                    attrs["pid"] = pid
                if generation is not None:
                    attrs["generation"] = generation
                attrs.update(extra_attrs)
                old_parent = entry.get("p")
                span = Span(
                    self, str(entry["n"]), trace_id,
                    id_map.get(entry.get("i"), next(self._ids)),
                    id_map.get(old_parent) if old_parent in id_map
                    else (parent.span_id if parent is not None else None),
                    attrs,
                )
                span.tid = int(entry.get("d", 0) or 0)
                span.start_ns = int(entry["s"]) + offset
                span.end_ns = int(entry["e"]) + offset
                grafted.append(span)
            except (KeyError, TypeError, ValueError):
                refused += 1
        with self._mu:
            for span in grafted:
                self._spans.append(span)
                self._finished += 1
            self._grafted += len(grafted)
            self._graft_dropped += refused + child_dropped
        return len(grafted)

    def mark(self) -> int:
        """Sequence checkpoint; pass to spans_since()/phase_ms_since()."""
        with self._mu:
            return self._finished

    def spans(self) -> List[Span]:
        with self._mu:
            return list(self._spans)

    def spans_since(self, seq: int) -> List[Span]:
        """Spans recorded after mark() returned `seq` (ring-aware: spans
        evicted since the mark are simply gone from the result)."""
        with self._mu:
            newer = self._finished - seq
            if newer <= 0:
                return []
            return list(self._spans)[-min(newer, len(self._spans)):]

    def phase_ms_since(self, seq: int, prefix: str = _PHASE_PREFIX,
                       last_only: bool = False) -> Dict[str, float]:
        """Per-phase milliseconds for solver.phase.* spans recorded after
        `seq` — the bench's phase-breakdown source. Default sums every
        occurrence (all relaxation rounds); last_only=True keeps only the
        final occurrence per phase, matching the historical
        last-round-overwrite timers so old bench artifacts stay comparable."""
        out: Dict[str, float] = {}
        for span in self.spans_since(seq):
            if span.name.startswith(prefix):
                key = span.name[len(prefix):]
                prev = 0.0 if last_only else out.get(key, 0.0)
                out[key] = round(prev + span.duration_ms, 1)
        return out

    # -- export ------------------------------------------------------------

    def chrome_trace(self) -> Dict[str, object]:
        """Chrome trace-event JSON (dict): complete ('X') events with
        microsecond ts/dur, loadable in Perfetto and chrome://tracing.
        Grafted child-process spans render under THEIR pid (a separate
        Perfetto process track, named by a metadata event), instant
        events (kills, respawns, breaker transitions) as 'i' markers —
        the multi-process solve timeline (ISSUE 15)."""
        events = []
        proc_names: Dict[int, str] = {self._pid: f"operator pid {self._pid}"}
        for span in self.spans():
            args = {"trace_id": span.trace_id, "span_id": span.span_id}
            if span.parent_id is not None:
                args["parent_id"] = span.parent_id
            for k, v in span.attrs.items():
                args[k] = v if isinstance(v, (int, float, bool)) else str(v)
            pid = span.attrs.get("pid")
            pid = pid if isinstance(pid, int) else self._pid
            if pid not in proc_names:
                gen = span.attrs.get("generation")
                proc_names[pid] = (
                    f"solver-host gen {gen} pid {pid}"
                    if isinstance(gen, int) else f"pid {pid}"
                )
            event = {
                "name": span.name,
                "cat": "karpenter",
                "ph": "X",
                "ts": (span.start_ns - self._t0_ns) / 1e3,
                "pid": pid,
                "tid": span.tid % 2**31,  # chrome wants a small int
                "args": args,
            }
            if span.attrs.get("instant") and span.start_ns == span.end_ns:
                event["ph"] = "i"
                event["s"] = "p"  # process-scoped marker line
            else:
                event["dur"] = max(span.end_ns - span.start_ns, 0) / 1e3
            events.append(event)
        for pid, label in sorted(proc_names.items()):
            events.append(
                {
                    "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                    "args": {"name": label},
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_spans": self.dropped,
                "grafted_spans": self.grafted,
                "graft_dropped": self.graft_dropped,
            },
        }

    def export_chrome_trace(self, path: str) -> str:
        # write-temp + atomic rename: a Perfetto/chrome tab polling the
        # trace file mid-export must never load a JSON prefix
        # (atomic-write rule, ISSUE 13)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def summary(self) -> str:
        """Compact per-span-name text summary (count / total / mean / max)."""
        agg: Dict[str, List[float]] = {}
        for span in self.spans():
            agg.setdefault(span.name, []).append(span.duration_ms)
        lines = [
            f"{'span':<40} {'count':>6} {'total_ms':>10} {'mean_ms':>9} {'max_ms':>9}"
        ]
        for name in sorted(agg):
            ds = agg[name]
            lines.append(
                f"{name:<40} {len(ds):>6} {sum(ds):>10.1f} "
                f"{sum(ds) / len(ds):>9.1f} {max(ds):>9.1f}"
            )
        if self.dropped:
            lines.append(f"(dropped {self.dropped} spans: ring buffer full)")
        return "\n".join(lines)


# frame-side export caps (ISSUE 15): the child's span delta riding a
# response/stats frame header is bounded in BOTH count and bytes, with the
# overflow counted in the payload's `dropped` — mirrored by the parent's
# per-graft cap (Tracer.MAX_GRAFT_SPANS)
MAX_EXPORT_SPANS = 256
MAX_EXPORT_BYTES = 131072


def _json_safe(value):
    return value if isinstance(value, (int, float, bool, str)) else str(value)


def export_spans(spans: List[Span], max_spans: int = MAX_EXPORT_SPANS,
                 max_bytes: int = MAX_EXPORT_BYTES) -> Dict[str, object]:
    """Serialize finished spans into the cross-process graft payload:

        {"pid": …, "now_ns": perf_counter_ns at export (the receiver's
         clock-rebase anchor), "spans": [{n,i,p,t,s,e,d,a}, …],
         "dropped": count NOT exported (count/byte cap overflow)}

    Newest spans win under the caps — the tail names the phases closest
    to the outcome. The payload is pure JSON (rides the solver-host frame
    header and the salvage spill file)."""
    window = spans[-max_spans:] if max_spans else []
    kept_rev: List[Dict[str, object]] = []
    size = 0
    dropped = len(spans) - len(window)
    for span in reversed(window):
        entry = {
            "n": span.name,
            "i": span.span_id,
            "t": span.trace_id,
            "s": span.start_ns,
            "e": span.end_ns,
            "d": span.tid,
        }
        if span.parent_id is not None:
            entry["p"] = span.parent_id
        if span.attrs:
            entry["a"] = {k: _json_safe(v) for k, v in span.attrs.items()}
        # cheap size proxy: the serialized entry's length; exact-enough to
        # bound the frame header without serializing the payload twice
        entry_size = len(json.dumps(entry, separators=(",", ":")))
        if size + entry_size > max_bytes:
            # everything older than the first overflow drops too (newest
            # spans win; counting them keeps truncation visible)
            dropped += len(window) - len(kept_rev)
            break
        size += entry_size
        kept_rev.append(entry)
    entries = list(reversed(kept_rev))
    return {
        "pid": os.getpid(),
        "now_ns": time.perf_counter_ns(),
        "spans": entries,
        "dropped": dropped,
    }


# the process-wide tracer
TRACER = Tracer()

from karpenter_core_tpu.obs import envflags  # noqa: E402
from karpenter_core_tpu.obs.envflags import FALSY as _FALSY, TRUTHY as _TRUTHY  # noqa: E402


def enable_tracing_from_env(default_on: bool = False) -> bool:
    """Arm/disarm TRACER from KARPENTER_TPU_TRACE — the ONE parser of that
    variable, shared by the import-time hook (default off) and the
    operator / solver-service entrypoints (default on), so truthy
    spellings like 'true'/'on' behave identically everywhere. Returns the
    resulting enabled state."""
    raw = envflags.raw("KARPENTER_TPU_TRACE").strip().lower()
    if raw in _FALSY:
        TRACER.disable()
    elif default_on or raw in _TRUTHY:
        TRACER.enable()
    return TRACER.enabled


# KARPENTER_TPU_TRACE set truthy arms tracing at import, so any entrypoint
# (bench, tests, one-off scripts) opts in uniformly
enable_tracing_from_env(default_on=False)


def profile_dir() -> str:
    """The device-profiling output directory, "" when profiling is off.
    The ONE place the KARPENTER_TPU_PROFILE / KARPENTER_JAX_TRACE_DIR
    (pre-ISSUE-1 spelling) env vars are interpreted — callers that need to
    know whether profiling is active (e.g. to barrier the dispatch) must
    use this instead of re-reading the env."""
    return (
        envflags.raw("KARPENTER_TPU_PROFILE")
        or envflags.raw("KARPENTER_JAX_TRACE_DIR")
    )


def device_profiler():
    """Context manager wrapping a device solve in jax.profiler when
    profile_dir() names a directory; no-op otherwise or when the profiler
    is unavailable. The captured trace is the device-side complement of
    this module's host spans (view with tensorboard/xprof)."""
    trace_dir = profile_dir()
    if trace_dir:
        try:
            import jax

            return jax.profiler.trace(trace_dir)
        except Exception:  # noqa: BLE001 — profiling is opt-in, never fatal
            pass
    return nullcontext()
