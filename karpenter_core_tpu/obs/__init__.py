"""Observability: end-to-end solve-path tracing, structured logging, and
the solve flight recorder.

The production hot path (provisioner reconcile -> batcher window ->
Scheduler.Solve() -> TPUSolver phases -> gRPC solver service -> bind) is
instrumented with the process-wide TRACER from obs.tracer; log lines join
spans through obs.log's trace-id correlation, and obs.flightrec captures
replayable per-Solve input/outcome records. Import the singletons from
here:

    from karpenter_core_tpu.obs import TRACER, FLIGHTREC, get_logger
"""
from karpenter_core_tpu.obs.flightrec import (
    FLIGHTREC,
    FlightRecorder,
    enable_flightrec_from_env,
)
from karpenter_core_tpu.obs.reqctx import (
    TENANTS,
    TENANT_HEADER,
    RequestContext,
    TenantGuard,
    bind as bind_request,
    current as current_request,
    current_tenant,
    tenant_labels,
)
from karpenter_core_tpu.obs.slo import Objective, SloEngine
from karpenter_core_tpu.obs.log import (
    SINK as LOG_SINK,
    bound as log_bound,
    configure_logging_from_env,
    get_logger,
)
from karpenter_core_tpu.obs.tracer import (
    TRACER,
    TRACE_HEADER,
    Span,
    Tracer,
    device_profiler,
    enable_tracing_from_env,
    export_spans,
    profile_dir,
)

__all__ = [
    "TRACER", "TRACE_HEADER", "Span", "Tracer", "device_profiler",
    "enable_tracing_from_env", "export_spans", "profile_dir",
    "LOG_SINK", "log_bound", "configure_logging_from_env", "get_logger",
    "FLIGHTREC", "FlightRecorder", "enable_flightrec_from_env",
    "TENANTS", "TENANT_HEADER", "RequestContext", "TenantGuard",
    "bind_request", "current_request", "current_tenant", "tenant_labels",
    "Objective", "SloEngine",
]
