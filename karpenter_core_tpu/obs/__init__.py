"""Observability: end-to-end solve-path tracing.

The production hot path (provisioner reconcile -> batcher window ->
Scheduler.Solve() -> TPUSolver phases -> gRPC solver service -> bind) is
instrumented with the process-wide TRACER from obs.tracer. Import the
singleton from here:

    from karpenter_core_tpu.obs import TRACER, device_profiler
"""
from karpenter_core_tpu.obs.tracer import (
    TRACER,
    TRACE_HEADER,
    Span,
    Tracer,
    device_profiler,
    enable_tracing_from_env,
    profile_dir,
)

__all__ = [
    "TRACER", "TRACE_HEADER", "Span", "Tracer", "device_profiler",
    "enable_tracing_from_env", "profile_dir",
]
