"""Declarative SLOs evaluated as multi-window burn rates.

An :class:`Objective` names a latency histogram, a good/bad threshold, and a
target fraction ("admission-to-bind p99 < 30s" becomes: 99% of observations
land in a bucket ≤ 30s). The :class:`SloEngine` samples the histogram's
cumulative series (the PR 6 ``Histogram.snapshot()`` shape: cumulative bucket
counts + count), diffs samples across sliding windows, and reports the
classic SRE burn rate per window:

    error_rate(window) / (1 - target)

A burn rate of 1.0 spends the error budget exactly at the sustainable pace;
14.4 over 5 minutes is the canonical page threshold. The budget-remaining
gauge is computed over the longest window (:attr:`SloEngine.budget_window_s`)
and exposed as ``karpenter_slo_error_budget_remaining{slo[,tenant]}``.

The engine is an *external exposition source* (PR 15's ``families()``
protocol): register it with ``REGISTRY.add_external(engine)`` and every
scrape computes fresh burn rates — no evaluation thread, and the gauge
family exists only where an engine is wired (the operator). Tenant series
come from the tenant labels the attribution plane already hangs off the
underlying histograms; the engine never invents label values, so it inherits
the ``reqctx.TENANTS`` cardinality cap.

The one control hook: :meth:`SloEngine.budget_exhausted` — the admission
gate's brownout band can prefer shedding tenants whose budget is spent
(off by default; see ``AdmissionGate.brownout_prefer``).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from karpenter_core_tpu.metrics.registry import NAMESPACE, Histogram

__all__ = [
    "BUDGET_GAUGE_NAME",
    "DEFAULT_BURN_WINDOWS",
    "Objective",
    "SloEngine",
]

BUDGET_GAUGE_NAME = f"{NAMESPACE}_slo_error_budget_remaining"

# (label, seconds) sliding windows burn rates are reported over. Short by
# SRE-book standards on purpose: the soak bench and obs-smoke drills live in
# minutes, not days, and the math is window-length agnostic.
DEFAULT_BURN_WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("1m", 60.0),
    ("5m", 300.0),
    ("1h", 3600.0),
)


@dataclass(frozen=True)
class Objective:
    """One SLO: `target` fraction of `histogram` observations ≤ `threshold_s`.

    ``base_labels`` narrows which series of the histogram belong to the
    objective (e.g. ``{"context": "provisioning"}`` on the solve-duration
    histogram); series are then grouped by their ``tenant`` label, with the
    tenant-less aggregate summed across all matching series.

    ``collect`` replaces the histogram read entirely: a callable returning
    ``{tenant-or-None: (good, total)}`` cumulative counts (the ``None`` key
    is the aggregate). This is how non-latency ratio objectives plug in —
    e.g. ``AdmissionGate.admission_totals``, where good = dispatched and
    bad = capacity sheds. With ``collect`` set, ``histogram`` may be None
    and ``threshold_s`` is ignored.
    """

    name: str
    histogram: Optional[Histogram]
    threshold_s: float
    target: float  # e.g. 0.99 — good fraction the SLO promises
    base_labels: Dict[str, str] = field(default_factory=dict)
    description: str = ""
    collect: Optional[Callable[[], Dict[Optional[str], Tuple[int, int]]]] = None


class _Sample:
    """One (timestamp, good-count, total-count) point for a series."""

    __slots__ = ("t", "good", "total")

    def __init__(self, t: float, good: int, total: int) -> None:
        self.t = t
        self.good = good
        self.total = total


class SloEngine:
    """Evaluates objectives as multi-window burn rates over histogram diffs."""

    def __init__(
        self,
        objectives: Sequence[Objective],
        windows: Tuple[Tuple[str, float], ...] = DEFAULT_BURN_WINDOWS,
        clock=time.monotonic,
        max_samples: int = 1024,
    ) -> None:
        self.objectives = tuple(objectives)
        self.windows = tuple(windows)
        self.budget_window_s = max(w for _, w in self.windows)
        self._clock = clock
        self._max_samples = int(max_samples)
        self._mu = threading.Lock()
        # (objective name, tenant-or-None) -> deque of _Sample, oldest first
        self._samples: Dict[Tuple[str, Optional[str]], Deque[_Sample]] = {}

    # -- sampling ----------------------------------------------------------

    def _good_index(self, obj: Objective) -> int:
        """Index of the largest bucket bound ≤ threshold (cumulative counts
        at that index == the good count). -1 when the threshold sits below
        every bucket (everything counts as bad)."""
        return bisect.bisect_right(obj.histogram.buckets, obj.threshold_s) - 1

    def _collect(self, obj: Objective) -> Dict[Optional[str], Tuple[int, int]]:
        """Current (good, total) per tenant for one objective. The None key
        is the aggregate: the sum over every matching series, so per-tenant
        observations still count toward the global objective."""
        if obj.collect is not None:
            try:
                collected = obj.collect()
            except Exception:  # noqa: BLE001 — a sick source reports nothing
                return {None: (0, 0)}
            out: Dict[Optional[str], Tuple[int, int]] = {}
            for tenant, pair in collected.items():
                good, total = pair
                out[tenant] = (int(good), int(total))
            out.setdefault(None, (0, 0))
            return out
        gi = self._good_index(obj)
        out: Dict[Optional[str], List[int]] = {None: [0, 0]}
        for labels, data in obj.histogram.series():
            if any(labels.get(k) != v for k, v in obj.base_labels.items()):
                continue
            extra = set(labels) - set(obj.base_labels)
            if extra - {"tenant"}:
                continue  # differently-shaped series (e.g. another context)
            counts = list(data.get("buckets", ()))
            total = int(data.get("count", 0))
            good = int(counts[gi]) if 0 <= gi < len(counts) else 0
            tenant = labels.get("tenant")
            agg = out[None]
            agg[0] += good
            agg[1] += total
            if tenant is not None:
                cur = out.setdefault(tenant, [0, 0])
                cur[0] += good
                cur[1] += total
        return {k: (v[0], v[1]) for k, v in out.items()}

    def sample(self) -> None:
        """Record one sample point per (objective, tenant) series."""
        now = self._clock()
        with self._mu:
            for obj in self.objectives:
                for tenant, (good, total) in self._collect(obj).items():
                    dq = self._samples.setdefault((obj.name, tenant), deque())
                    if not dq:
                        # zero baseline for a first-seen series: a tenant
                        # that appears mid-run burns from its first window
                        # instead of hiding behind a missing baseline
                        dq.append(_Sample(now, 0, 0))
                    dq.append(_Sample(now, good, total))
                    while len(dq) > self._max_samples:
                        dq.popleft()
                    horizon = now - 2 * self.budget_window_s
                    while len(dq) > 1 and dq[0].t < horizon:
                        dq.popleft()

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _window_rates(dq: Deque[_Sample], now: float, window_s: float,
                      target: float) -> Tuple[Optional[float], int]:
        """(burn rate, window traffic) for one series over one window.
        Clamps to observed history: the baseline is the newest sample at
        least `window_s` old, else the oldest we have. None when the window
        saw no traffic."""
        if not dq:
            return None, 0
        newest = dq[-1]
        base = dq[0]
        for s in reversed(dq):
            if now - s.t >= window_s:
                base = s
                break
        total = newest.total - base.total
        if total <= 0:
            return None, 0
        good = newest.good - base.good
        error_rate = 1.0 - (good / total)
        allowed = 1.0 - target
        burn = error_rate / allowed if allowed > 0 else (0.0 if error_rate == 0 else float("inf"))
        return burn, total

    def evaluate(self) -> List[dict]:
        """Sample, then report every (objective, tenant) series: burn rate
        per window plus budget remaining over the longest window (1.0 =
        untouched, 0.0 = spent, negative = overdrawn)."""
        self.sample()
        now = self._clock()
        out: List[dict] = []
        with self._mu:
            for obj in self.objectives:
                for (name, tenant), dq in sorted(
                    self._samples.items(),
                    key=lambda kv: (kv[0][0], kv[0][1] or ""),
                ):
                    if name != obj.name:
                        continue
                    burns = {}
                    for wname, wsec in self.windows:
                        burn, traffic = self._window_rates(dq, now, wsec, obj.target)
                        burns[wname] = {"burn_rate": burn, "traffic": traffic}
                    budget_burn, traffic = self._window_rates(
                        dq, now, self.budget_window_s, obj.target
                    )
                    remaining = 1.0 if budget_burn is None else 1.0 - budget_burn
                    out.append({
                        "slo": obj.name,
                        "tenant": tenant,
                        "target": obj.target,
                        "threshold_s": obj.threshold_s,
                        "description": obj.description,
                        "windows": burns,
                        "budget_window_s": self.budget_window_s,
                        "budget_remaining": remaining,
                        "traffic": traffic,
                    })
        return out

    def fast_burn(self, tenant: Optional[str]) -> float:
        """Max burn rate for *tenant* over the SHORTEST window, across all
        objectives — the brownout ladder's demotion signal (the fast window
        reacts in seconds where the budget window takes its full span to
        drain). Takes a fresh sample, so callers should rate-limit (the
        ladder's ``eval_interval_s`` does). 0.0 for unknown tenants or
        windows with no traffic."""
        self.sample()
        if tenant is None:
            return 0.0
        now = self._clock()
        fast_s = min(w for _, w in self.windows)
        worst = 0.0
        with self._mu:
            for obj in self.objectives:
                dq = self._samples.get((obj.name, tenant))
                if not dq:
                    continue
                burn, _ = self._window_rates(dq, now, fast_s, obj.target)
                if burn is not None and burn > worst:
                    worst = burn
        return worst

    def budget_exhausted(self, tenant: Optional[str]) -> bool:
        """True when any objective's budget for *tenant* is spent (≤ 0) over
        the budget window. Unknown tenants have burned nothing. This is the
        signal the admission gate's brownout-preference hook consumes."""
        if tenant is None:
            return False
        now = self._clock()
        with self._mu:
            for obj in self.objectives:
                dq = self._samples.get((obj.name, tenant))
                if not dq:
                    continue
                burn, _ = self._window_rates(dq, now, self.budget_window_s, obj.target)
                if burn is not None and burn >= 1.0:
                    return True
        return False

    # -- exposition (external source protocol, PR 15) ----------------------

    def families(self) -> Dict[str, dict]:
        """Gauge family for the registry's external-source hook. Tenant-less
        aggregates carry only the `slo` label — a run that never bound a
        tenant exposes no `tenant` label here either."""
        series: List[Tuple[Dict[str, str], float]] = []
        for row in self.evaluate():
            labels = {"slo": row["slo"]}
            if row["tenant"] is not None:
                labels["tenant"] = row["tenant"]
            series.append((labels, row["budget_remaining"]))
        return {
            BUDGET_GAUGE_NAME: {
                "kind": "gauge",
                "help": "SLO error budget remaining over the budget window "
                        "(1 = untouched, <=0 = exhausted)",
                "series": series,
            }
        }

    def digest(self) -> dict:
        """JSON digest for /debug/slo."""
        return {
            "windows": [{"name": n, "seconds": s} for n, s in self.windows],
            "budget_window_s": self.budget_window_s,
            "objectives": [
                {
                    "name": o.name,
                    "target": o.target,
                    "threshold_s": o.threshold_s,
                    "histogram": (
                        o.histogram.name if o.histogram is not None
                        else None
                    ),
                    "source": (
                        "collect" if o.collect is not None else "histogram"
                    ),
                    "base_labels": dict(o.base_labels),
                    "description": o.description,
                }
                for o in self.objectives
            ],
            "series": self.evaluate(),
        }
