"""Shared truthy/falsy env-var spellings for the obs/ arming hooks
(KARPENTER_TPU_TRACE / KARPENTER_TPU_LOG / KARPENTER_TPU_FLIGHTREC), so the
three parsers cannot drift. The empty string is deliberately NOT in FALSY:
each parser decides what "unset" means (tracer/flightrec leave state to the
entrypoint default; the log parser treats it as off)."""

TRUTHY = ("1", "true", "on", "yes")
FALSY = ("0", "false", "off", "no")
