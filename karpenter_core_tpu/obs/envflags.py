"""The package's single funnel for environment configuration.

Every env read in karpenter_core_tpu/ routes through these accessors — the
`env-flags` lint rule (analysis/envdiscipline.py) bans direct os.environ /
os.getenv use anywhere else. One funnel means the truthy/falsy grammar
can't drift between parsers, the knob surface is greppable in one place,
and tests monkeypatching os.environ keep working (reads stay live, nothing
is cached here).

TRUTHY/FALSY are the shared spellings for the obs/ arming hooks
(KARPENTER_TPU_TRACE / KARPENTER_TPU_LOG / KARPENTER_TPU_FLIGHTREC). The
empty string is deliberately NOT in FALSY: each parser decides what
"unset" means (tracer/flightrec leave state to the entrypoint default; the
log parser treats it as off).
"""
from __future__ import annotations

import os
from typing import Mapping

TRUTHY = ("1", "true", "on", "yes")
FALSY = ("0", "false", "off", "no")


def raw(name: str, default: str = "") -> str:
    """os.environ.get with a string default — the universal accessor for
    callers that do their own parsing."""
    return os.environ.get(name, default)


def require(name: str) -> str:
    """Read a mandatory variable; KeyError (with the variable name) when
    unset — for knobs like KARPENTER_DIST_NUM_PROCESSES that have no sane
    default once their feature is enabled."""
    return os.environ[name]


def get_bool(name: str, default: bool = False) -> bool:
    """Parse TRUTHY/FALSY spellings; unset or unrecognized -> default."""
    value = os.environ.get(name, "").strip().lower()
    if value in TRUTHY:
        return True
    if value in FALSY:
        return False
    return default


def environ() -> Mapping[str, str]:
    """The live process environment, for callers that take a mapping
    parameter (chaos.arm_from_env) — still a funnel: the mapping identity
    is handed out, never copied, so monkeypatched entries are visible."""
    return os.environ
