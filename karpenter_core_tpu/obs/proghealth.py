"""Compiled-program cost inventory — where the device milliseconds live.

The solver's four compiled-program LRUs (solve/refresh/replan/segment,
solver/tpu_solver.py) were opaque: program COUNT is tripwired
(test_perf_floor.py) but nothing observed program COST — per-key compile
seconds, execution counts, device milliseconds, HLO flop/byte estimates,
peak-HBM footprint. That is exactly the evidence ROADMAP item 5 needs to
decide which rungs to fold, prewarm, or delete, and what a real-TPU round
(ROADMAP item 1) must ship home to make the north-star claim measured
instead of asserted.

Three pieces, all stdlib-only (no jax import — analysis operates on the
compiled executables the solver hands in, by duck typing, so this module
keeps working when the accelerator stack is absent or wedged):

  * ``ProgramLedger`` — per-process inventory every mint/dispatch/eviction
    reports into. Bounded (MAX_RECORDS), lock-protected, and free on the
    disabled path: each record_* funnel is gated on one flag check before
    any allocation (tripwired in test_perf_floor.py).
  * ``normalize_cost_analysis`` / ``analyze_compiled`` — the portability
    shim over ``compiled.cost_analysis()`` (jax versions differ on
    list-of-dicts vs dict returns) and ``compiled.memory_analysis()``;
    the API shape is probed ONCE per ledger and recorded, and every
    fallback (CPU backend, older jax, missing executable) returns
    ``"unavailable"`` — never raises.
  * ``ProgramInventoryMerger`` — the PR 15 generation contract applied to
    program snapshots riding the solver-host stats frame: ``ingest``
    replaces the live view for a generation, a generation bump or
    ``retire`` folds that generation's cumulative totals into the base
    exactly once (respawn-idempotent), and every surviving entry carries
    the ``process`` label.

The operator's gated ``/debug/programs`` serves ``full_snapshot()`` (the
local ledger plus every registered source, e.g. the solver host's child
merger), and ``EXPOSITION`` renders the summary metric families
(``karpenter_program_count`` / ``_compile_seconds_total`` /
``_hbm_peak_bytes``) as a Registry external source.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, Optional

from karpenter_core_tpu.obs import envflags

# live records per ledger; eviction-retired records fold into totals, so
# the bound is on live-program cardinality (itself LRU-bounded upstream)
MAX_RECORDS = 256
# entries served per /debug/programs snapshot (deterministic order)
MAX_SNAPSHOT_PROGRAMS = 128
# EMA smoothing for per-record device milliseconds
EMA_ALPHA = 0.2

FAMILIES = ("solve", "refresh", "replan", "segment")

_TOTAL_FIELDS = ("minted", "retired", "exec_total", "compile_seconds_total")


def _key_digest(key) -> str:
    """Stable short digest of a compiled-program cache key (keys carry
    treedefs and layout objects whose reprs are stable within a process —
    good enough for a debugging identity, never for equality)."""
    return hashlib.blake2s(repr(key).encode(), digest_size=6).hexdigest()


def normalize_cost_analysis(raw) -> Optional[Dict[str, float]]:
    """Normalize a ``compiled.cost_analysis()`` return to one schema.

    jax has shipped BOTH a list-of-dicts (one per device/computation) and
    a bare dict from this API across versions; downstream must never care.
    Returns ``{"flops": float, "bytes_accessed": float}`` (keys present
    only when the backend reported them) or None when the shape is
    unrecognized or empty.
    """
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out: Dict[str, float] = {}
    flops = raw.get("flops")
    if isinstance(flops, (int, float)):
        out["flops"] = float(flops)
    acc = raw.get("bytes accessed", raw.get("bytes_accessed"))
    if isinstance(acc, (int, float)):
        out["bytes_accessed"] = float(acc)
    return out or None


def _memory_peak_bytes(mem) -> Optional[int]:
    """Peak-HBM estimate from a ``memory_analysis()`` return: the explicit
    peak when the backend reports one, else the sum of the sized sections
    (arguments + outputs + temps + generated code)."""
    if mem is None:
        return None
    peak = getattr(mem, "peak_memory_in_bytes", None)
    if isinstance(peak, (int, float)) and peak > 0:
        return int(peak)
    total = 0
    seen = False
    for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "generated_code_size_in_bytes"):
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            total += int(v)
            seen = True
    return total if seen else None


class ProgramLedger:
    """Per-process compiled-program inventory (mint / dispatch / retire).

    Per-key records carry geometry tier, scan/screen mode, AOT-vs-live
    origin, compile seconds, exec count, last/EMA device ms, and — where
    the backend supports it — normalized cost/memory analysis. Family
    totals (minted/retired/exec/compile-seconds) are cumulative and
    monotone: eviction retires the record but never the seconds it cost.
    """

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = (
            envflags.get_bool("KARPENTER_PROGHEALTH", True)
            if enabled is None else bool(enabled)
        )
        self._mu = threading.Lock()
        self._records: Dict[tuple, dict] = {}
        self._totals: Dict[str, Dict[str, float]] = {}
        # cost_analysis API shape, probed once on the first successful
        # call this ledger sees: "list" | "dict" | "unavailable" | None
        self._cost_shape: Optional[str] = None

    # -- analysis ----------------------------------------------------------

    def analyze_compiled(self, compiled) -> Dict[str, object]:
        """Bounded, never-raising cost/memory probe of one executable.
        The first successful cost_analysis records the API shape this jax
        ships (the list-vs-dict portability hazard, probed once)."""
        out: Dict[str, object] = {"cost": "unavailable",
                                  "memory": "unavailable"}
        if compiled is None:
            return out
        try:
            raw = compiled.cost_analysis()
            with self._mu:
                if self._cost_shape is None:
                    self._cost_shape = (
                        "list" if isinstance(raw, (list, tuple)) else
                        "dict" if isinstance(raw, dict) else "unavailable"
                    )
            cost = normalize_cost_analysis(raw)
            if cost is not None:
                out["cost"] = cost
        except Exception:  # noqa: BLE001 — observability never raises
            with self._mu:
                if self._cost_shape is None:
                    self._cost_shape = "unavailable"
        try:
            peak = _memory_peak_bytes(compiled.memory_analysis())
            if peak is not None:
                out["memory"] = {"hbm_peak_bytes": int(peak)}
        except Exception:  # noqa: BLE001
            pass
        return out

    # -- totals ------------------------------------------------------------

    def _bump_locked(self, family: str, field: str, delta: float) -> None:
        fam = self._totals.setdefault(
            family, {f: 0 for f in _TOTAL_FIELDS}
        )
        fam[field] = fam.get(field, 0) + delta

    # -- record funnels ----------------------------------------------------

    def record_mint(self, family: str, key, origin: str = "live",
                    compile_s: float = 0.0, compiled=None,
                    meta: Optional[dict] = None) -> None:
        """A program was built at `key` (the compile event). `compiled` is
        the AOT executable when one exists (live-path jit objects have no
        inspectable executable until a later AOT attach)."""
        if not self.enabled:
            return
        rec = {
            "family": family,
            "key": _key_digest(key),
            "origin": origin,
            "compile_seconds": round(float(compile_s), 6),
            "exec_count": 0,
            "last_device_ms": None,
            "ema_device_ms": None,
        }
        if meta:
            rec.update(meta)
        rec.update(self.analyze_compiled(compiled))
        with self._mu:
            fresh = (family, rec["key"]) not in self._records
            self._records[(family, rec["key"])] = rec
            if fresh:
                self._bump_locked(family, "minted", 1)
            if compile_s:
                self._bump_locked(
                    family, "compile_seconds_total", float(compile_s)
                )
            while len(self._records) > MAX_RECORDS:
                old = next(iter(self._records))
                del self._records[old]
                self._bump_locked(old[0], "retired", 1)

    def record_compile(self, family: str, key, seconds: float,
                       compiled=None) -> None:
        """Attribute compile seconds discovered AFTER the mint — the live
        path pays jit trace + XLA compile at first dispatch, not at
        record_mint time."""
        if not self.enabled:
            return
        digest = _key_digest(key)
        with self._mu:
            rec = self._records.get((family, digest))
            if rec is not None:
                rec["compile_seconds"] = round(
                    rec.get("compile_seconds", 0.0) + float(seconds), 6
                )
            self._bump_locked(
                family, "compile_seconds_total", float(seconds)
            )
        if compiled is not None and rec is not None:
            analysis = self.analyze_compiled(compiled)
            with self._mu:
                rec.update(analysis)

    def record_dispatch(self, family: str, key, device_ms=None) -> None:
        """One execution of the program at `key`. Hot path: the disabled
        gate above is the whole cost when the ledger is off."""
        if not self.enabled:
            return
        digest = _key_digest(key)
        with self._mu:
            rec = self._records.get((family, digest))
            if rec is None:
                # dispatch observed for a program minted before this
                # ledger existed (or already evicted): count it under a
                # synthetic record so exec totals stay truthful
                rec = {
                    "family": family, "key": digest, "origin": "unknown",
                    "compile_seconds": 0.0, "exec_count": 0,
                    "last_device_ms": None, "ema_device_ms": None,
                    "cost": "unavailable", "memory": "unavailable",
                }
                self._records[(family, digest)] = rec
                self._bump_locked(family, "minted", 1)
            rec["exec_count"] += 1
            self._bump_locked(family, "exec_total", 1)
            if device_ms is not None:
                ms = float(device_ms)
                rec["last_device_ms"] = round(ms, 3)
                prev = rec["ema_device_ms"]
                rec["ema_device_ms"] = round(
                    ms if prev is None
                    else EMA_ALPHA * ms + (1.0 - EMA_ALPHA) * prev, 3
                )

    def retire(self, family: str, key) -> None:
        """The LRU evicted `key`: drop the live record, keep its cumulative
        contribution in the family totals (exactly-once per record)."""
        if not self.enabled:
            return
        digest = _key_digest(key)
        with self._mu:
            if self._records.pop((family, digest), None) is not None:
                self._bump_locked(family, "retired", 1)

    def clear(self) -> None:
        with self._mu:
            self._records = {}
            self._totals = {}
            self._cost_shape = None

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able inventory: bounded program list (deterministic family,
        key order) + cumulative family totals. Rides the solver-host stats
        frame, so it must stay small and sort-stable."""
        with self._mu:
            records = [dict(r) for r in self._records.values()]
            totals = {f: dict(t) for f, t in self._totals.items()}
            shape = self._cost_shape
        records.sort(key=lambda r: (r["family"], r["key"]))
        dropped = max(0, len(records) - MAX_SNAPSHOT_PROGRAMS)
        out = {
            "programs": records[:MAX_SNAPSHOT_PROGRAMS],
            "totals": totals,
            "cost_shape": shape,
        }
        if dropped:
            out["dropped"] = dropped
        return out


class ProgramInventoryMerger:
    """Merged view over one child process's program-inventory snapshots —
    the ProcessSeriesMerger contract (metrics/registry.py) applied to the
    program plane: ingest REPLACES a generation's live view, a generation
    bump or retire folds that generation's cumulative totals into the
    committed base exactly once, and a dead child's live program entries
    drop (its records died with the process; its compile seconds did not).
    """

    def __init__(self, process: str = "solver-host"):
        self.process = process
        self._mu = threading.Lock()
        self._live: dict = {}
        self._live_gen: Optional[int] = None
        self._base_totals: Dict[str, Dict[str, float]] = {}

    def _fold_live_locked(self) -> None:
        for fam, tot in (self._live.get("totals") or {}).items():
            base = self._base_totals.setdefault(fam, {})
            for field, value in tot.items():
                if isinstance(value, (int, float)):
                    base[field] = base.get(field, 0) + value
        self._live = {}
        self._live_gen = None

    def ingest(self, generation: int, snap: dict) -> None:
        if not isinstance(snap, dict):
            return
        with self._mu:
            if self._live_gen is not None and generation != self._live_gen:
                self._fold_live_locked()
            self._live_gen = generation
            self._live = snap

    def retire(self, generation: int) -> None:
        with self._mu:
            if self._live_gen == generation:
                self._fold_live_locked()

    def clear(self) -> None:
        with self._mu:
            self._live = {}
            self._live_gen = None
            self._base_totals = {}

    def snapshot(self) -> dict:
        with self._mu:
            gen = self._live_gen
            programs = [
                dict(r, process=self.process, generation=gen)
                for r in (self._live.get("programs") or ())
            ]
            totals: Dict[str, Dict[str, float]] = {
                f: dict(t) for f, t in self._base_totals.items()
            }
            for fam, tot in (self._live.get("totals") or {}).items():
                base = totals.setdefault(fam, {})
                for field, value in tot.items():
                    if isinstance(value, (int, float)):
                        base[field] = base.get(field, 0) + value
            out = {"programs": programs, "totals": totals,
                   "process": self.process}
            shape = self._live.get("cost_shape")
            if shape is not None:
                out["cost_shape"] = shape
            return out


# -- module singletons -------------------------------------------------------

LEDGER = ProgramLedger()

# extra inventory sources for the unified /debug/programs view, keyed by
# process name (e.g. "solver-host" -> the HostSolver merger's snapshot);
# latest registration per name wins, sources must never raise
_SOURCES: Dict[str, Callable[[], dict]] = {}
_sources_mu = threading.Lock()


def reset(enabled: Optional[bool] = None) -> ProgramLedger:
    """Replace the process ledger (tests + entrypoints re-arming after an
    env change). Returns the new ledger."""
    global LEDGER
    LEDGER = ProgramLedger(enabled=enabled)
    return LEDGER


def add_source(name: str, fn: Callable[[], dict]) -> None:
    with _sources_mu:
        _SOURCES[name] = fn


def remove_source(name: str, fn: Optional[Callable] = None) -> None:
    with _sources_mu:
        if fn is None or _SOURCES.get(name) is fn:
            _SOURCES.pop(name, None)


# thin module-level funnels: call sites stay one import away from the
# live singleton (reset() swaps it atomically), and the disabled path is
# one attribute load + one flag check before any work
def record_mint(family, key, origin="live", compile_s=0.0, compiled=None,
                meta=None):
    led = LEDGER
    if led.enabled:
        led.record_mint(family, key, origin=origin, compile_s=compile_s,
                        compiled=compiled, meta=meta)


def record_compile(family, key, seconds, compiled=None):
    led = LEDGER
    if led.enabled:
        led.record_compile(family, key, seconds, compiled=compiled)


def record_dispatch(family, key, device_ms=None):
    led = LEDGER
    if led.enabled:
        led.record_dispatch(family, key, device_ms)


def retire(family, key):
    led = LEDGER
    if led.enabled:
        led.retire(family, key)


def full_snapshot() -> dict:
    """The unified inventory: the local ledger's programs (process="main")
    plus every registered source's (already process-labeled). Served at
    /debug/programs and summarized by EXPOSITION."""
    local = LEDGER.snapshot()
    programs = [dict(r, process="main") for r in local["programs"]]
    totals: Dict[str, dict] = {"main": local["totals"]}
    with _sources_mu:
        sources = dict(_SOURCES)
    for name, fn in sorted(sources.items()):
        try:
            snap = fn()
        except Exception:  # noqa: BLE001 — a sick source must not kill the view
            continue
        if not isinstance(snap, dict):
            continue
        programs.extend(snap.get("programs") or ())
        totals[name] = snap.get("totals") or {}
    out = {
        "enabled": LEDGER.enabled,
        "programs": programs,
        "totals": totals,
    }
    if local.get("cost_shape") is not None:
        out["cost_shape"] = local["cost_shape"]
    return out


class ProgramExposition:
    """Registry external source summarizing the unified inventory into the
    karpenter_program_* families: live program count and max peak-HBM as
    gauges, cumulative compile seconds as a counter — per (process,
    family) series, so a compile-collapse regression or a child paying
    repeated restart compiles is one /metrics scrape away."""

    def families(self) -> Dict[str, dict]:
        snap = full_snapshot()
        count: Dict[tuple, int] = {}
        hbm: Dict[tuple, int] = {}
        for rec in snap["programs"]:
            lk = (rec.get("process", "main"), rec.get("family", "?"))
            count[lk] = count.get(lk, 0) + 1
            mem = rec.get("memory")
            if isinstance(mem, dict):
                peak = mem.get("hbm_peak_bytes")
                if isinstance(peak, (int, float)):
                    hbm[lk] = max(hbm.get(lk, 0), int(peak))
        compile_s: Dict[tuple, float] = {}
        for process, fams in snap["totals"].items():
            for fam, tot in (fams or {}).items():
                sec = tot.get("compile_seconds_total")
                if isinstance(sec, (int, float)) and sec:
                    compile_s[(process, fam)] = float(sec)

        def _series(data):
            return [
                [{"process": p, "family": f}, v]
                for (p, f), v in sorted(data.items())
            ]

        out: Dict[str, dict] = {}
        if count:
            out["karpenter_program_count"] = {
                "kind": "gauge",
                "help": "Live compiled programs by process and family.",
                "series": _series(count),
            }
        if compile_s:
            out["karpenter_program_compile_seconds_total"] = {
                "kind": "counter",
                "help": "Cumulative XLA compile seconds by process and "
                        "family (eviction never subtracts).",
                "series": _series(compile_s),
            }
        if hbm:
            out["karpenter_program_hbm_peak_bytes"] = {
                "kind": "gauge",
                "help": "Max peak-HBM estimate among live programs by "
                        "process and family (memory_analysis).",
                "series": _series(hbm),
            }
        return out


EXPOSITION = ProgramExposition()


def ensure_exposition_registered() -> None:
    """Idempotently attach EXPOSITION to the process metrics registry
    (add_external dedupes by identity)."""
    from karpenter_core_tpu.metrics.registry import REGISTRY

    REGISTRY.add_external(EXPOSITION)
