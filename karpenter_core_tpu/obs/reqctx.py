"""Bounded request/tenant attribution context.

Every entry point into the solver plane — operator reconcile, gRPC metadata,
the solver-host frame header — binds a :class:`RequestContext` for the
duration of the request. Downstream instrumentation (admission gate, fallback
ladder, tracer, flight recorder, compile cache) reads the context through
:func:`current_tenant` / :func:`tenant_labels` and attaches a ``tenant`` label
to the series it already emits.

Two hard contracts, both tripwired in ``tests/test_perf_floor.py``:

* **Zero cost when unset.** With no context bound, :func:`current_tenant` is
  a thread-local list check, :func:`tenant_labels` allocates nothing beyond
  the label dict the call site already paid for, and the solver-host frame
  header gains no key (same absent-key contract as the ``trace`` header).
* **Bounded cardinality.** Tenant label *values* route through the module
  :data:`TENANTS` guard: a fixed slot table (:data:`DEFAULT_TENANT_CAP`)
  after which every new tenant collapses into the :data:`OVERFLOW_TENANT`
  label. A label flood can therefore never blow up exposition or the
  cross-process ``ProcessSeriesMerger``. The ``metric-labels`` lint pass
  enforces that ``tenant`` label values at metric call sites are produced by
  this guard.

Wire header / gRPC metadata key for the tenant: :data:`TENANT_HEADER`.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    "DEFAULT_TENANT_CAP",
    "OVERFLOW_TENANT",
    "RequestContext",
    "TENANTS",
    "TENANT_HEADER",
    "TenantGuard",
    "bind",
    "current",
    "current_deadline",
    "current_tenant",
    "tenant_labels",
]

# gRPC metadata key and solver-host frame-header key carrying the tenant.
TENANT_HEADER = "x-karpenter-tenant"

# Fixed tenant-slot cap; tenants past the cap share the overflow label.
DEFAULT_TENANT_CAP = 16
OVERFLOW_TENANT = "other"


@dataclass(frozen=True)
class RequestContext:
    """What one request is, for attribution: who, which, how urgent.

    ``tenant`` is the only field that becomes a metric label (through the
    cardinality guard); the rest ride along in logs, spans, and flight
    records where unbounded values are safe.
    """

    tenant: Optional[str] = None
    request_id: Optional[str] = None
    priority: int = 0
    deadline_s: Optional[float] = None


class _Stack(threading.local):
    def __init__(self) -> None:  # per-thread init
        self.items: List[RequestContext] = []


_STACK = _Stack()


@contextlib.contextmanager
def bind(ctx: RequestContext) -> Iterator[RequestContext]:
    """Bind *ctx* as the calling thread's request context for the block.

    Also pushes the context's identity fields onto the structured-log
    bound-context stack, so every log line emitted under the bind carries
    tenant/request_id without the call sites knowing about attribution."""
    # call-time import: reqctx is the bottom of the obs stack (log/tracer
    # both import it), so the upward edge to log must not be module-scope
    from karpenter_core_tpu.obs import log as _log

    _STACK.items.append(ctx)
    fields: Dict[str, object] = {}
    if ctx.tenant is not None:
        fields["tenant"] = ctx.tenant
    if ctx.request_id is not None:
        fields["request_id"] = ctx.request_id
    try:
        if fields:
            with _log.bound(**fields):
                yield ctx
        else:
            yield ctx
    finally:
        _STACK.items.pop()


def current() -> Optional[RequestContext]:
    """The innermost bound context, or None."""
    items = _STACK.items
    return items[-1] if items else None


def current_tenant() -> Optional[str]:
    """Tenant of the innermost bound context, or None. O(1), no allocation."""
    items = _STACK.items
    return items[-1].tenant if items else None


def current_deadline() -> Optional[float]:
    """``deadline_s`` (remaining budget) of the innermost bound context, or
    None. O(1), no allocation. The admission gate reads this to tighten a
    request's queue budget and to order it within its tenant's EDF
    sub-queue."""
    items = _STACK.items
    return items[-1].deadline_s if items else None


class TenantGuard:
    """Fixed-slot tenant-label interner: the cardinality guard.

    The first :attr:`cap` distinct tenants each get their own label; every
    tenant after that maps to :data:`OVERFLOW_TENANT`. ``admit`` is the only
    way a request-derived string becomes a metric label value.
    """

    def __init__(self, cap: int = DEFAULT_TENANT_CAP) -> None:
        self.cap = int(cap)
        self._mu = threading.Lock()
        self._slots: Dict[str, str] = {}
        self._overflowed = 0

    def admit(self, tenant: Optional[str]) -> Optional[str]:
        """Guarded label for *tenant* (None passes through as None)."""
        if tenant is None:
            return None
        tenant = str(tenant)
        with self._mu:
            label = self._slots.get(tenant)
            if label is None:
                if len(self._slots) < self.cap:
                    label = self._slots[tenant] = tenant
                else:
                    self._overflowed += 1
                    label = OVERFLOW_TENANT
            return label

    def tenants(self) -> Tuple[str, ...]:
        """Admitted tenant labels, sorted."""
        with self._mu:
            return tuple(sorted(self._slots))

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {"slots": len(self._slots), "cap": self.cap, "overflowed": self._overflowed}

    def reset(self) -> None:
        """Drop all slots (tests only — live series keep their labels)."""
        with self._mu:
            self._slots.clear()
            self._overflowed = 0


# Process-wide guard. Parent and solver-host child each have their own
# instance; both cap at the same slot count so the merged series set stays
# bounded on both sides of the frame protocol.
TENANTS = TenantGuard()


def tenant_labels(**base: str) -> Optional[Dict[str, str]]:
    """Label dict for a metric call site, with the bound tenant folded in.

    No tenant bound: returns *base* unchanged (or None when empty) — zero
    allocations beyond the kwargs dict the call already paid for. Tenant
    bound: adds ``tenant=<guarded label>`` to *base*.
    """
    tenant = current_tenant()
    if tenant is None:
        return base or None
    base["tenant"] = TENANTS.admit(tenant)
    return base
